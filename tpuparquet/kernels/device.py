"""Device decode orchestration: column chunks -> device-resident columns.

The cuDF-style batch-decode backend of BASELINE.json: raw page bytes are
staged to device memory and decoded by vectorized kernels; the host only
parses headers and builds plan tables.  Output is Arrow-layout
:class:`DeviceColumn` objects (packed values + validity + levels), which
``to_numpy()`` materializes in exactly the CPU oracle's representation for
bit-exact parity checks.

Current device coverage (the rest falls back to the CPU oracle per value
segment, still staged into the same DeviceColumn):

* PLAIN int32/int64/float/double/int96/FLBA (reinterpret staging)
* PLAIN boolean (width-1 unpack)
* RLE_DICTIONARY indices (run-table expand) + dictionary gather,
  fixed-width and variable-width (byte-level gather)
* definition/repetition levels (run-table expand) + validity fusion
* DELTA_BINARY_PACKED int32
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..compress import decompress_block
from ..cpu import decode_plain
from ..cpu.plain import ByteArrayColumn
from ..format.compact import CompactReader
from ..format.metadata import (
    ColumnMetaData,
    CompressionCodec,
    Encoding,
    PageHeader,
    PageType,
    Type,
    decode_struct,
)
from ..format.schema import SchemaNode
from .bitunpack import pad_to_words, unpack_u32
from .decode import (
    dict_gather_bytes,
    dict_gather_fixed,
    expand_delta_i32,
    levels_to_validity,
    plain_fixed_to_lanes,
    plan_delta_i32,
    stage_u32,
)
from .hybrid import decode_hybrid_device

__all__ = ["DeviceColumn", "decode_chunk_device", "read_row_group_device"]

_LANES = {
    Type.INT32: 1, Type.FLOAT: 1, Type.INT64: 2, Type.DOUBLE: 2,
    Type.INT96: 3,
}

_DICT_ENCODINGS = (Encoding.PLAIN_DICTIONARY, Encoding.RLE_DICTIONARY)


class DeviceColumn:
    """Device-resident decoded column (Arrow layout).

    ``data``: (n_non_null, lanes) u32 for fixed-width types, or u8 bytes
    with ``offsets`` for BYTE_ARRAY.  ``mask``/``positions`` map record
    slots to packed values; ``rep_levels``/``def_levels`` preserve nesting.

    Buffers are stored *bucket-padded* (the shape the fused page kernels
    emit) with logical lengths ``num_values`` (record slots) and
    ``n_packed`` (non-null values); the public accessors slice lazily and
    materialize implicit streams (all-zero levels, all-valid masks) on
    demand, so the common flat-required case costs zero extra dispatches.
    """

    __slots__ = ("ptype", "type_length", "offsets", "num_values",
                 "n_packed", "n_bytes", "_data_p", "_mask_p", "_pos_p",
                 "_rep_p", "_def_p", "_cache")

    def __init__(self, ptype, type_length, data, offsets, mask, positions,
                 rep_levels, def_levels, num_values, n_packed=None,
                 n_bytes=None):
        self.ptype = ptype
        self.type_length = type_length
        self._data_p = data
        self.offsets = offsets
        self._mask_p = mask
        self._pos_p = positions
        self._rep_p = rep_levels
        self._def_p = def_levels
        self.num_values = num_values
        self.n_packed = (
            n_packed if n_packed is not None
            else (None if data is None else data.shape[0])
        )
        self.n_bytes = n_bytes  # BYTE_ARRAY only: logical data length
        self._cache = {}

    # -- lazy exact-shape accessors ---------------------------------------

    def _sliced(self, key, padded, n, fill):
        got = self._cache.get(key)
        if got is None:
            if padded is None:
                got = fill()
            elif padded.shape[0] == n:
                got = padded
            else:
                got = padded[:n]
            self._cache[key] = got
        return got

    @property
    def data(self):
        if self.offsets is not None:
            # BYTE_ARRAY: the buffer axis is bytes, not values
            return self._sliced(
                "data", self._data_p, self.n_bytes,
                lambda: jnp.zeros((0,), dtype=jnp.uint8))
        return self._sliced(
            "data", self._data_p, self.n_packed,
            lambda: jnp.zeros((0, 1), dtype=jnp.uint32))

    @property
    def mask(self):
        return self._sliced(
            "mask", self._mask_p, self.num_values,
            lambda: jnp.ones((self.num_values,), dtype=bool))

    @property
    def positions(self):
        return self._sliced(
            "pos", self._pos_p, self.num_values,
            lambda: jnp.arange(self.num_values, dtype=jnp.int32))

    @property
    def rep_levels(self):
        return self._sliced(
            "rep", self._rep_p, self.num_values,
            lambda: jnp.zeros((self.num_values,), dtype=jnp.int32))

    @property
    def def_levels(self):
        return self._sliced(
            "def", self._def_p, self.num_values,
            lambda: jnp.zeros((self.num_values,), dtype=jnp.int32))

    def block_until_ready(self):
        for x in (self._data_p, self.offsets, self._mask_p, self._rep_p,
                  self._def_p):
            if x is not None:
                x.block_until_ready()
        return self

    def to_numpy(self):
        """Materialize to the CPU oracle's chunk representation:
        (values, rep_levels, def_levels).  Slices padding host-side."""
        n = self.num_values
        rep = (np.zeros(n, dtype=np.int32) if self._rep_p is None
               else np.asarray(self._rep_p, dtype=np.int32)[:n])
        dl = (np.zeros(n, dtype=np.int32) if self._def_p is None
              else np.asarray(self._def_p, dtype=np.int32)[:n])
        if self.offsets is not None:
            offs = np.asarray(self.offsets, dtype=np.int64)
            data = np.asarray(self._data_p, dtype=np.uint8)[: int(offs[-1])]
            return ByteArrayColumn(offs, data), rep, dl
        lanes = np.asarray(self._data_p, dtype=np.uint32)[: self.n_packed]
        if self.ptype == Type.BOOLEAN:
            return lanes.reshape(-1).astype(bool), rep, dl
        if self.ptype == Type.INT32:
            return lanes.reshape(-1).view(np.int32), rep, dl
        if self.ptype == Type.FLOAT:
            return lanes.reshape(-1).view(np.float32), rep, dl
        if self.ptype == Type.INT64:
            return lanes.reshape(-1).view(np.uint8).view("<i8"), rep, dl
        if self.ptype == Type.DOUBLE:
            return lanes.reshape(-1).view(np.uint8).view("<f8"), rep, dl
        if self.ptype == Type.INT96:
            return lanes.reshape(-1, 3), rep, dl
        if self.ptype == Type.FIXED_LEN_BYTE_ARRAY:
            n = self.type_length
            return (
                lanes.reshape(-1).view(np.uint8).reshape(-1, 4 * lanes.shape[1])[:, :n],
                rep, dl,
            )
        raise TypeError(f"unsupported type {self.ptype}")


def _stage_fixed_plain(raw: bytes, count: int, ptype: Type,
                       type_length) -> jax.Array:
    if ptype == Type.BOOLEAN:
        words = pad_to_words(np.frombuffer(raw, np.uint8), 1, count)
        return unpack_u32(jnp.asarray(words), 1, count)[:, None]
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        return _stage_byte_rows(
            np.frombuffer(raw, np.uint8, count * type_length).reshape(
                count, type_length
            )
        )
    lanes = _LANES[ptype]
    words = stage_u32(raw, count * lanes)
    return plain_fixed_to_lanes(jnp.asarray(words), count, lanes)


def _flba_lanes(type_length: int) -> int:
    return (type_length + 3) // 4


def _stage_byte_rows(arr: np.ndarray) -> jax.Array:
    """(N, L) u8 rows -> (N, lanes) u32, zero-padding each row to whole
    little-endian u32 lanes (shared FLBA/int96 staging)."""
    rows = arr.view(np.uint8).reshape(arr.shape[0], -1)
    lanes = _flba_lanes(rows.shape[1])
    padded = np.zeros((rows.shape[0], lanes * 4), dtype=np.uint8)
    padded[:, : rows.shape[1]] = rows
    return jnp.asarray(padded.reshape(-1, lanes, 4).view("<u4")[..., 0])


def decode_chunk_device(blob, cm: ColumnMetaData, node: SchemaNode,
                        base: int = 0) -> DeviceColumn:
    """Decode one column chunk to a DeviceColumn.

    ``blob`` holds the chunk's byte range; offsets in ``cm`` are absolute
    minus ``base``.  Host work: page-header walk, block decompression
    (until the device snappy path lands), plan building.
    """
    codec = CompressionCodec(cm.codec)
    ptype = Type(node.element.type)
    start = cm.data_page_offset
    if cm.dictionary_page_offset is not None:
        start = min(start, cm.dictionary_page_offset)
    start -= base
    end = start + cm.total_compressed_size
    r = CompactReader(blob, start, end)

    dict_fixed = None      # staged (D, lanes) u32
    dict_offsets = None    # staged byte-array dictionary
    dict_data = None
    dict_lens_np = None
    dict_np = None

    val_parts = []         # [(device (n,lanes) u32 possibly padded, n)]
    bytes_parts = []       # (offsets_np, device u8 data, total_bytes)
    rep_parts = []         # [(device i32 possibly padded, n)] — only maxR>0
    def_parts = []         # [(device i32 possibly padded, n)] — only maxD>0
    values_read = 0
    total = cm.num_values
    max_def = node.max_def_level
    dwidth = max_def.bit_length()

    while values_read < total:
        ph = decode_struct(PageHeader, r)
        payload = bytes(blob[r.pos : r.pos + ph.compressed_page_size])
        r.pos += ph.compressed_page_size
        ptype_page = PageType(ph.type)

        if ptype_page == PageType.DICTIONARY_PAGE:
            raw = decompress_block(codec, payload, ph.uncompressed_page_size)
            dict_np = decode_plain(
                ptype, raw, ph.dictionary_page_header.num_values,
                node.element.type_length,
            )
            if isinstance(dict_np, ByteArrayColumn):
                dict_offsets = jnp.asarray(dict_np.offsets, dtype=jnp.int32)
                dict_data = jnp.asarray(dict_np.data)
                dict_lens_np = dict_np.lengths()
            else:
                arr = np.asarray(dict_np)
                if arr.dtype == np.bool_:
                    staged = arr.astype(np.uint32)[:, None]
                elif arr.dtype in (np.dtype("<i4"), np.dtype("<f4")):
                    staged = arr.view("<u4")[:, None]
                elif arr.dtype in (np.dtype("<i8"), np.dtype("<f8")):
                    staged = arr.view("<u4").reshape(-1, 2)
                elif ptype == Type.INT96:
                    staged = arr.astype("<u4")
                else:  # FLBA (D, L) u8
                    staged = _stage_byte_rows(arr)
                dict_fixed = jnp.asarray(staged)
            if r.pos != cm.data_page_offset - base:
                r.pos = cm.data_page_offset - base
            continue

        if ptype_page == PageType.DATA_PAGE:
            h = ph.data_page_header
            raw = decompress_block(codec, payload, ph.uncompressed_page_size)
            n = h.num_values
            pos = 0
            if node.max_rep_level:
                rep_dev, pos, _, _ = _levels_v1_device(
                    raw, n, node.max_rep_level, pos,
                    h.repetition_level_encoding,
                )
                rep_parts.append((rep_dev, n))
            dl_scan, dl_host, pos = _scan_levels_v1(
                raw, n, max_def, pos, h.definition_level_encoding
            )
            values_seg = raw[pos:]
            enc = h.encoding
        elif ptype_page == PageType.DATA_PAGE_V2:
            h = ph.data_page_header_v2
            n = h.num_values
            rl_len = h.repetition_levels_byte_length or 0
            dl_len = h.definition_levels_byte_length or 0
            if node.max_rep_level:
                rep_dev, _ = _levels_raw_device(
                    payload[:rl_len], n, node.max_rep_level
                )
                rep_parts.append((rep_dev, n))
            dl_scan, dl_host = (None, None)
            if max_def:
                from ..cpu.hybrid import scan_hybrid

                dl_scan = scan_hybrid(
                    payload[rl_len : rl_len + dl_len], n, dwidth
                )
            values_seg = payload[rl_len + dl_len :]
            if h.is_compressed is not False:
                values_seg = decompress_block(
                    codec, values_seg,
                    ph.uncompressed_page_size - rl_len - dl_len,
                )
            enc = h.encoding
        else:
            continue

        if not max_def:
            non_null = n
        elif (ptype_page == PageType.DATA_PAGE_V2
              and h.num_nulls is not None):
            non_null = n - h.num_nulls
        elif dl_scan is not None:
            # count non-nulls from the run table (RLE arithmetic + one
            # vectorized unpack) rather than syncing the device expansion
            # back — device->host round-trips serialize the page pipeline
            from .hybrid import count_eq_scan

            non_null = count_eq_scan(dl_scan, dwidth, max_def,
                                     validate_max=True)
        else:
            non_null = int((dl_host == max_def).sum())
        values_read += n

        # Def-level plan, padded for the fused page kernels.  A page
        # whose value path can't fuse expands it standalone below.
        dl_args = dl_cnt = dl_nbp = None
        if dl_scan is not None:
            from .hybrid import pad_plan, plan_from_scan

            dl_args, dl_cnt, _, dl_nbp = pad_plan(
                plan_from_scan(dl_scan, n, dwidth)
            )
        elif dl_host is not None:
            def_parts.append((jnp.asarray(dl_host, dtype=jnp.int32), n))

        def _def_standalone():
            """Expand the def plan on its own (non-fused value paths)."""
            if dl_args is not None:
                from .hybrid import expand_hybrid

                dl_dev = expand_hybrid(
                    *jax.device_put(dl_args), dl_cnt, dwidth, dl_nbp
                ).astype(jnp.int32)
                def_parts.append((dl_dev, n))

        if enc in _DICT_ENCODINGS:
            width = values_seg[0] if len(values_seg) else 0
            if dict_fixed is not None:
                from .decode import page_dict_fixed, page_dict_fixed_levels
                from .hybrid import pad_plan as _pp, plan_from_scan as _pf
                from ..cpu.hybrid import scan_hybrid

                i_sc = scan_hybrid(values_seg, non_null, width, pos=1) \
                    if width else None
                if i_sc is None:
                    idx_args = None
                else:
                    idx_args, i_cnt, _, i_nbp = _pp(
                        _pf(i_sc, non_null, width)
                    )
                if dl_args is not None and idx_args is not None:
                    staged = jax.device_put((dl_args, idx_args))
                    vals, dl_dev = page_dict_fixed_levels(
                        dict_fixed, *staged[0], *staged[1],
                        dl_cnt, dwidth, dl_nbp, i_cnt, width, i_nbp,
                    )
                    def_parts.append((dl_dev, n))
                    val_parts.append((vals, non_null))
                else:
                    _def_standalone()
                    if idx_args is None:
                        idx = jnp.zeros((non_null,), jnp.int32)
                        val_parts.append(
                            (dict_gather_fixed(dict_fixed, idx), non_null)
                        )
                    else:
                        vals = page_dict_fixed(
                            dict_fixed, *jax.device_put(idx_args),
                            i_cnt, width, i_nbp,
                        )
                        val_parts.append((vals, non_null))
            elif dict_offsets is not None:
                # host-side index decode (vectorized, no device sync) just
                # to size the output; the gather uses the device indices
                from ..cpu.hybrid import decode_hybrid
                from .decode import bucket
                from .hybrid import decode_hybrid_device_padded

                _def_standalone()
                idx_np = (
                    decode_hybrid(values_seg, non_null, width, pos=1)
                    .astype(np.int32)
                    if width else np.zeros(non_null, np.int32)
                )
                lens = dict_lens_np[idx_np]
                out_offsets = np.zeros(non_null + 1, dtype=np.int32)
                np.cumsum(lens, out=out_offsets[1:])
                total_b = int(out_offsets[-1])
                # every dynamic input stays at its bucket size so the jit
                # cache keys on buckets, not exact per-page counts
                cap = bucket(max(total_b, 1))
                idx_pad = decode_hybrid_device_padded(
                    values_seg, non_null, width, pos=1
                ).astype(jnp.int32) if width else jnp.zeros(
                    (bucket(max(non_null, 1)),), jnp.int32
                )
                offs_pad = np.full(idx_pad.shape[0] + 1, total_b,
                                   dtype=np.int32)
                offs_pad[: non_null + 1] = out_offsets
                data = dict_gather_bytes(
                    dict_offsets, dict_data, idx_pad,
                    jnp.asarray(offs_pad), cap,
                )
                bytes_parts.append((out_offsets, data, total_b))
            else:
                raise ValueError("dict-encoded page without dictionary")
        elif enc == Encoding.PLAIN:
            if ptype == Type.BYTE_ARRAY:
                _def_standalone()
                col = decode_plain(ptype, values_seg, non_null)  # host scan
                offs = col.offsets.astype(np.int32)
                bytes_parts.append(
                    (offs, jnp.asarray(col.data), int(col.data.size))
                )
            elif (dl_args is not None
                  and ptype not in (Type.BOOLEAN,
                                    Type.FIXED_LEN_BYTE_ARRAY)):
                from .decode import page_plain_fixed_levels

                lanes = _LANES[ptype]
                words = stage_u32(values_seg, non_null * lanes)
                staged = jax.device_put((words, dl_args))
                vals, dl_dev = page_plain_fixed_levels(
                    staged[0], *staged[1], non_null, lanes,
                    dl_cnt, dwidth, dl_nbp,
                )
                def_parts.append((dl_dev, n))
                val_parts.append((vals, non_null))
            else:
                _def_standalone()
                val_parts.append((
                    _stage_fixed_plain(values_seg, non_null, ptype,
                                       node.element.type_length),
                    non_null,
                ))
        elif enc == Encoding.DELTA_BINARY_PACKED and ptype == Type.INT32:
            _def_standalone()
            plan = plan_delta_i32(values_seg)
            val_parts.append(
                (expand_delta_i32(plan)[:non_null, None], non_null)
            )
        else:
            # CPU fallback for the remaining encodings; stage the result.
            _def_standalone()
            col = decode_values_cpu(ptype, enc, values_seg, non_null,
                                    node.element.type_length)
            if isinstance(col, ByteArrayColumn):
                bytes_parts.append(
                    (col.offsets.astype(np.int32), jnp.asarray(col.data),
                     int(col.data.size))
                )
            else:
                val_parts.append((_stage_numpy_fixed(col, ptype), non_null))

    rep, _ = _merge_parts(rep_parts)
    dl, _ = _merge_parts(def_parts)
    if max_def and dl is not None:
        mask, positions = levels_to_validity(dl, max_def)
    else:
        mask = positions = None

    if bytes_parts:
        if len(bytes_parts) == 1:
            offs_np, data, nbytes = bytes_parts[0]
            offsets = jnp.asarray(offs_np.astype(np.int64))
            return DeviceColumn(ptype, node.element.type_length, data,
                                offsets, mask, positions, rep, dl, total,
                                n_packed=len(offs_np) - 1, n_bytes=nbytes)
        # merge per-page byte columns: rebase offsets, concat data
        all_offs = [np.zeros(1, dtype=np.int64)]
        datas = []
        base_off = 0
        for offs, data, nbytes in bytes_parts:
            all_offs.append(np.asarray(offs[1:], dtype=np.int64) + base_off)
            datas.append(jnp.asarray(data)[:nbytes])
            base_off += nbytes
        offsets = jnp.asarray(np.concatenate(all_offs))
        data = jnp.concatenate(datas) if datas else jnp.zeros(0, jnp.uint8)
        return DeviceColumn(ptype, node.element.type_length, data, offsets,
                            mask, positions, rep, dl, total,
                            n_packed=sum(len(o) for o in all_offs) - 1,
                            n_bytes=base_off)

    data, n_packed = _merge_parts(val_parts)
    return DeviceColumn(ptype, node.element.type_length, data, None, mask,
                        positions, rep, dl, total, n_packed=n_packed or 0)


def _merge_parts(parts):
    """Merge [(padded device array, logical n)] -> (array, total n).

    Single-part chunks keep their padding (consumers slice lazily);
    multi-part chunks slice then concatenate."""
    if not parts:
        return None, 0
    if len(parts) == 1:
        return parts[0]
    arrs = [a if a.shape[0] == m else a[:m] for a, m in parts]
    return jnp.concatenate(arrs), sum(m for _, m in parts)


def read_row_group_device(reader, rg_index: int) -> dict[str, DeviceColumn]:
    """Decode the selected columns of one row group onto the device.

    The device-path sibling of ``FileReader.read_row_group_arrays``: same
    selection semantics, device-resident results."""
    rg = reader.meta.row_groups[rg_index]
    out = {}
    for path, node, cm, blob, start in reader.iter_selected_chunks(rg):
        out[path] = decode_chunk_device(memoryview(blob), cm, node,
                                        base=start)
    return out


def decode_values_cpu(ptype, enc, data, count, type_length):
    from ..io.pages import decode_values

    return decode_values(ptype, enc, data, count, type_length)


def _stage_numpy_fixed(col, ptype: Type) -> jax.Array:
    arr = np.asarray(col)
    if arr.dtype == np.bool_:
        return jnp.asarray(arr.astype(np.uint32)[:, None])
    if arr.dtype.itemsize == 4:
        return jnp.asarray(arr.view("<u4").reshape(-1, 1))
    if arr.dtype.itemsize == 8:
        return jnp.asarray(arr.view("<u4").reshape(-1, 2))
    if arr.ndim == 2:  # FLBA / int96 byte matrices
        return _stage_byte_rows(arr)
    raise TypeError(f"cannot stage {arr.dtype} for {ptype}")


def _scan_levels_v1(raw, n, max_level, pos, encoding=Encoding.RLE):
    """Scan a V1 def-level stream without expanding it.

    Returns (scan | None, host levels | None, end pos); expansion happens
    inside the fused page kernel (or standalone for non-fused paths)."""
    if max_level == 0:
        return None, None, pos
    width = max_level.bit_length()
    if encoding == Encoding.BIT_PACKED:
        from ..cpu import decode_levels_bitpacked

        nbytes = (n * width + 7) // 8
        vals = decode_levels_bitpacked(raw[pos : pos + nbytes], n, max_level)
        return None, vals, pos + nbytes
    import struct

    from ..cpu.hybrid import scan_hybrid

    (size,) = struct.unpack_from("<I", raw, pos)
    sc = scan_hybrid(raw[pos + 4 : pos + 4 + size], n, width)
    return sc, None, pos + 4 + size


def _levels_v1_device(raw, n, max_level, pos, encoding=Encoding.RLE):
    """Returns (device levels, end pos, scan | None, host levels | None).

    The scan (run table) is returned so callers can count non-nulls from
    it without re-decoding; host levels are populated instead when the
    decode already happened on host (BIT_PACKED)."""
    if max_level == 0:
        return jnp.zeros((n,), dtype=jnp.int32), pos, None, None
    width = max_level.bit_length()
    if encoding == Encoding.BIT_PACKED:
        # Legacy MSB-first levels (old parquet-mr writers): decode on host
        # via the oracle and stage — rare enough not to warrant a kernel.
        from ..cpu import decode_levels_bitpacked

        nbytes = (n * width + 7) // 8
        vals = decode_levels_bitpacked(raw[pos : pos + nbytes], n, max_level)
        return jnp.asarray(vals, dtype=jnp.int32), pos + nbytes, None, vals
    import struct

    from ..cpu.hybrid import scan_hybrid
    from .hybrid import expand_plan_padded, plan_from_scan

    (size,) = struct.unpack_from("<I", raw, pos)
    body = raw[pos + 4 : pos + 4 + size]
    sc = scan_hybrid(body, n, width)
    vals = expand_plan_padded(plan_from_scan(sc, n, width))[:n]
    return vals.astype(jnp.int32), pos + 4 + size, sc, None


def _levels_raw_device(raw, n, max_level):
    """Returns (device levels, scan | None) for V2 unprefixed levels."""
    if max_level == 0:
        return jnp.zeros((n,), dtype=jnp.int32), None
    width = max_level.bit_length()
    from ..cpu.hybrid import scan_hybrid
    from .hybrid import expand_plan_padded, plan_from_scan

    sc = scan_hybrid(raw, n, width)
    vals = expand_plan_padded(plan_from_scan(sc, n, width))[:n]
    return vals.astype(jnp.int32), sc
