"""Recycled host staging buffers for the device decode path.

Fresh multi-megabyte numpy allocations fault in new pages on every
write, which caps every first-touch copy at a fraction of warm-memory
bandwidth (measured ~3x slower on single-core hosts).  The plan phase
allocates the same page-sized buffers every row group — decompression
outputs, staging words — so a generation-scoped free list recycles them.

Lifetime contract: ``borrow`` hands out a whole slab per call (borrowers
never alias); ``release_all`` returns every outstanding slab to the free
list.  Callers must release only after all device transfers reading from
these buffers have completed (``jax.block_until_ready`` on everything
dispatched from them).
"""

from __future__ import annotations

import threading

import numpy as np

__all__ = ["HostArena", "thread_arena", "discard_thread_arena"]


class HostArena:
    """Best-fit free list of reusable u8 slabs."""

    __slots__ = ("_free", "_used", "max_slabs")

    def __init__(self, max_slabs: int = 64):
        self._free: list[np.ndarray] = []
        self._used: list[np.ndarray] = []
        self.max_slabs = max_slabs

    def borrow(self, nbytes: int) -> np.ndarray:
        """A u8 array of exactly ``nbytes``, backed by a recycled slab
        when one fits (smallest sufficient slab wins)."""
        best = -1
        for i, s in enumerate(self._free):
            if s.size >= nbytes and (
                best < 0 or s.size < self._free[best].size
            ):
                best = i
        if best >= 0:
            slab = self._free.pop(best)
        else:
            # round up so nearby page sizes share slabs
            cap = max(nbytes, 4096)
            cap = 1 << (cap - 1).bit_length()
            slab = np.empty(cap, dtype=np.uint8)
        self._used.append(slab)
        return slab[:nbytes]

    def release_all(self) -> None:
        """Return every borrowed slab; keep only the largest slabs when
        over the cap so a one-off giant row group doesn't pin memory
        forever while small pages churn."""
        free = self._free + self._used
        self._used = []
        if len(free) > self.max_slabs:
            free.sort(key=lambda s: s.size)
            free = free[-self.max_slabs:]
        self._free = free


_local = threading.local()


def thread_arena() -> HostArena:
    """The calling thread's arena (one per thread: slabs are not
    shareable across concurrent borrowers)."""
    a = getattr(_local, "arena", None)
    if a is None:
        a = _local.arena = HostArena()
    return a


def discard_thread_arena() -> None:
    """Drop the calling thread's arena without releasing its slabs.

    The error-path escape hatch: when device transfers sourced from
    arena-backed views may still be in flight after an exception, the
    slabs cannot be recycled safely — abandoning the arena lets the
    transfers finish against memory nothing else will touch (numpy
    frees it only once JAX's references drop)."""
    _local.arena = None
