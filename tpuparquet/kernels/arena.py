"""Recycled host staging buffers for the device decode path.

Fresh multi-megabyte numpy allocations fault in new pages on every
write, which caps every first-touch copy at a fraction of warm-memory
bandwidth (measured ~3x slower on single-core hosts).  The plan phase
allocates the same page-sized buffers every row group — decompression
outputs, staging words — so a generation-scoped free list recycles them.

Lifetime contract: ``borrow`` hands out a whole slab per call (borrowers
never alias); ``release_all`` returns every outstanding slab to the free
list.  Callers must release only after all device transfers reading from
these buffers have completed (``jax.block_until_ready`` on everything
dispatched from them).
"""

from __future__ import annotations

import threading

import numpy as np

__all__ = ["HostArena", "ArenaPool", "lease_arena", "return_arena",
           "trim_arena_pool", "set_arena_retention", "thread_arena",
           "discard_thread_arena", "arena_occupancy", "take_arena_peak"]


# ----------------------------------------------------------------------
# Process-wide occupancy watermark (attribution telemetry)
# ----------------------------------------------------------------------
# Outstanding borrowed slab bytes across every arena, plus the
# high-water mark since the last take — the "peak arena bytes" a
# per-scan resource ledger (obs/attribution.py) reports.  Borrow-time
# adds happen under a lock (a couple of integer ops per slab borrow —
# slab, not page, granularity); readers take the peak at unit
# boundaries.  Best-effort on abandoned arenas: an arena dropped
# without release (the in-flight-transfer escape hatch) subtracts its
# outstanding bytes at finalization.

_occ_lock = threading.Lock()
_occ_bytes = 0
_occ_peak = 0


def _occ_add(n: int) -> None:
    global _occ_bytes, _occ_peak
    with _occ_lock:
        _occ_bytes += n
        if _occ_bytes > _occ_peak:
            _occ_peak = _occ_bytes


def _occ_sub(n: int) -> None:
    global _occ_bytes
    with _occ_lock:
        _occ_bytes = max(_occ_bytes - n, 0)


def arena_occupancy() -> int:
    """Outstanding borrowed arena bytes right now (process-wide)."""
    with _occ_lock:
        return _occ_bytes


def take_arena_peak() -> int:
    """The occupancy high-water mark since the previous take; resets
    the mark to the CURRENT occupancy (so successive takes window the
    peak without ever under-reporting a still-outstanding borrow).

    PROCESS-WIDE by construction: arenas are a shared pool, so the
    watermark cannot say which scan's borrows produced a given peak —
    with concurrent scans, whichever scan takes a window first
    absorbs that window's (shared) peak.  Per-scan ledgers therefore
    report this as "peak arena occupancy observed during my units",
    an upper bound on the scan's own footprint, not an exact
    per-tenant attribution."""
    global _occ_peak
    with _occ_lock:
        p = _occ_peak
        _occ_peak = _occ_bytes
        return p


class HostArena:
    """Best-fit free list of reusable u8 slabs."""

    __slots__ = ("_free", "_used", "_used_bytes", "max_slabs")

    def __init__(self, max_slabs: int = 64):
        self._free: list[np.ndarray] = []
        self._used: list[np.ndarray] = []
        self._used_bytes = 0
        self.max_slabs = max_slabs

    def borrow(self, nbytes: int) -> np.ndarray:
        """A u8 array of exactly ``nbytes``, backed by a recycled slab
        when one fits (smallest sufficient slab wins)."""
        best = -1
        for i, s in enumerate(self._free):
            if s.size >= nbytes and (
                best < 0 or s.size < self._free[best].size
            ):
                best = i
        if best >= 0:
            slab = self._free.pop(best)
        else:
            # round up so nearby page sizes share slabs
            cap = max(nbytes, 4096)
            cap = 1 << (cap - 1).bit_length()
            slab = np.empty(cap, dtype=np.uint8)
        self._used.append(slab)
        self._used_bytes += slab.size
        _occ_add(slab.size)
        return slab[:nbytes]

    def release_all(self) -> None:
        """Return every borrowed slab; keep only the largest slabs when
        over the cap so a one-off giant row group doesn't pin memory
        forever while small pages churn."""
        free = self._free + self._used
        self._used = []
        _occ_sub(self._used_bytes)
        self._used_bytes = 0
        if len(free) > self.max_slabs:
            free.sort(key=lambda s: s.size)
            free = free[-self.max_slabs:]
        self._free = free

    def __del__(self):
        # abandoned arenas (error paths drop leases without release so
        # in-flight transfers stay safe) must not pin the occupancy
        # gauge forever; interpreter-shutdown partial teardown tolerated
        try:
            if self._used_bytes:
                _occ_sub(self._used_bytes)
        except Exception:
            pass


class ArenaPool:
    """Process-wide pool of :class:`HostArena` leases for the
    column-parallel plan tasks.

    Each plan task leases a WHOLE arena for its duration, so racing
    planners never share a slab (the old per-unit arena would be
    written by several column planners at once).  Leases are returned
    only after the unit's transfers have drained
    (``_finish_row_group``'s batched ``block_until_ready``), which is
    the same lifetime contract ``HostArena.release_all`` documents —
    the pool just moves the recycling boundary from thread-local to
    task-scoped.  Error paths simply DROP their lease references
    (never ``give_back``): the slabs may still back in-flight
    transfers, and numpy frees them once JAX's references drop."""

    __slots__ = ("_lock", "_free", "max_arenas")

    def __init__(self, max_arenas: int = 8):
        self._lock = threading.Lock()
        self._free: list[HostArena] = []
        # retention cap on FREE arenas only (in-flight leases are
        # unbounded — they are the scan's working set); a wide-core
        # scan's give_backs beyond the cap free their slabs instead of
        # pinning high-watermark memory for the process lifetime
        self.max_arenas = max_arenas

    def lease(self) -> HostArena:
        with self._lock:
            if self._free:
                return self._free.pop()
        return HostArena()

    def give_back(self, arena: HostArena) -> None:
        """Recycle an arena (caller guarantees every transfer sourced
        from its slabs has completed)."""
        arena.release_all()
        with self._lock:
            if len(self._free) < self.max_arenas:
                self._free.append(arena)

    def trim(self, keep: int = 0) -> None:
        """Drop free arenas beyond ``keep`` (scan-end hook: long-lived
        processes should not carry a finished scan's slab high-water
        mark)."""
        with self._lock:
            del self._free[keep:]

    def set_retention(self, max_arenas: int) -> int:
        """Adjust the free-list cap; returns the previous cap.  The
        serve layer raises it to the global worker budget (every
        concurrent tenant worker churns a lease) and restores it on
        shutdown."""
        with self._lock:
            prev = self.max_arenas
            self.max_arenas = max(int(max_arenas), 0)
        return prev


_POOL = ArenaPool()


def lease_arena() -> HostArena:
    """Lease a per-task arena from the shared pool."""
    return _POOL.lease()


def return_arena(arena: HostArena) -> None:
    """Return a leased arena to the shared pool for recycling."""
    _POOL.give_back(arena)


def trim_arena_pool(keep: int = 0) -> None:
    """Release the shared pool's retained free arenas (see
    :meth:`ArenaPool.trim`); called by the pipelined reader when a
    scan ends, and available to long-lived hosts."""
    _POOL.trim(keep)


def set_arena_retention(max_arenas: int) -> int:
    """Adjust the shared pool's free-list cap (see
    :meth:`ArenaPool.set_retention`); returns the previous cap."""
    return _POOL.set_retention(max_arenas)


_local = threading.local()


def thread_arena() -> HostArena:
    """The calling thread's arena (one per thread: slabs are not
    shareable across concurrent borrowers)."""
    a = getattr(_local, "arena", None)
    if a is None:
        a = _local.arena = HostArena()
    return a


def discard_thread_arena() -> None:
    """Drop the calling thread's arena without releasing its slabs.

    The error-path escape hatch: when device transfers sourced from
    arena-backed views may still be in flight after an exception, the
    slabs cannot be recycled safely — abandoning the arena lets the
    transfers finish against memory nothing else will touch (numpy
    frees it only once JAX's references drop)."""
    _local.arena = None
