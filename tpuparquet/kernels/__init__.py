"""Device (JAX/XLA/Pallas) decode/encode kernels and orchestration."""

from .bitunpack import unpack_u32, unpack_u32_pallas, pad_to_words  # noqa: F401
from .encode import (  # noqa: F401
    DeviceValues,
    bss_encode_device,
    delta_encode_device,
    pack_u32_device,
    pack_u64_device,
)
from .decode import (  # noqa: F401
    dict_gather_bytes,
    dict_gather_fixed,
    expand_delta_i32,
    levels_to_validity,
    plan_delta_i32,
    scatter_to_dense,
)
from .device import (  # noqa: F401
    DeviceColumn,
    decode_chunk_device,
    read_row_group_device,
)
from .hybrid import decode_hybrid_device, expand_hybrid, plan_hybrid  # noqa: F401
