"""Footer-keyed plan cache: transport decisions survive re-reads.

The device plan phase runs a wire-cost competition per PLAIN
fixed-width page (snappy tokens vs byte-plane RLE vs delta lanes —
``kernels/device.py``): entropy sample windows, token scans, full
min/max passes.  Those decisions are deterministic given the file's
bytes, and the footer pins the file's identity — a re-read of an
unchanged file re-derives exactly the same verdicts.  This cache keys
the per-page *plan artifact* (transport choice + the small geometry
needed to rebuild it: delta width/min, per-lane plane specs) by
``(footer fingerprint, row group, column)`` so epoch-style training
re-reads skip the competition and go straight to committing the known
winner.  Staged bytes are NEVER cached — every read restages from the
file, so a hint can only change *which lossless transport* ships, not
the decoded bytes.

Budgeted LRU: ``TPQ_PLAN_CACHE_MB`` (default off — 0/unset disables
everything, lookups return None and nothing is stored).  Counters
``plan_cache_hits`` / ``plan_cache_misses`` / ``plan_cache_evictions``
ride :class:`~tpuparquet.stats.DecodeStats` and merge exactly across
workers and hosts.  Invalidation:

* a rewritten/salvage-rescued file carries a new footer, so its key
  changes and stale entries age out of the LRU;
* salvaged readers get no fingerprint at all (``FileReader`` leaves
  ``plan_fingerprint`` None) — recovered files never populate or hit;
* a corruption event (CRC mismatch, corrupt page/chunk) during a plan
  drops every entry under that fingerprint
  (:func:`invalidate_fingerprint`) — the file on disk may no longer be
  what the footer claims.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

import numpy as np

__all__ = ["PlanCache", "plan_cache", "plan_cache_budget",
           "set_plan_cache_budget",
           "invalidate_fingerprint", "clear_plan_cache"]

_override_lock = threading.Lock()
_budget_override_mb: float | None = None


def set_plan_cache_budget(mb: float | None) -> float | None:
    """Programmatic budget override (MB; None clears it): the serve
    layer arms a shared plan cache for its lifetime without mutating
    the process environment.  Returns the previous override so a
    server restores what it found on shutdown."""
    global _budget_override_mb
    with _override_lock:
        prev = _budget_override_mb
        _budget_override_mb = mb
    return prev


def plan_cache_budget() -> int:
    """Cache byte budget (0 = disabled): the programmatic override
    when one is set (:func:`set_plan_cache_budget`), else
    ``TPQ_PLAN_CACHE_MB``.  Read per call so same-process A/B runs
    can flip it."""
    mb = _budget_override_mb
    if mb is not None:
        return max(int(float(mb) * (1 << 20)), 0)
    v = os.environ.get("TPQ_PLAN_CACHE_MB")
    if not v:
        return 0
    try:
        return max(int(float(v) * (1 << 20)), 0)
    except ValueError:
        return 0


def _entry_nbytes(record) -> int:
    """LRU accounting: approximate in-memory size of one column's page
    hint list (tuples of small ints/strings plus the per-lane plane
    specs, which may carry a tiny cost array)."""
    n = 96  # key + OrderedDict node overhead
    for h in record:
        n += 56
        if h is None:
            continue
        params = h[2] if len(h) > 2 else None
        if params is None:
            continue
        if isinstance(params, (list, tuple)):
            for p in params:
                n += 48
                if isinstance(p, tuple):
                    for q in p:
                        n += (q.nbytes if isinstance(q, np.ndarray)
                              else 28)
        else:
            n += 48
    return n


class PlanCache:
    """Thread-safe byte-budgeted LRU of per-column page-hint lists."""

    __slots__ = ("_lock", "_entries", "_bytes")

    def __init__(self):
        self._lock = threading.Lock()
        # key -> (record, nbytes); move_to_end on hit = LRU order
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0

    def lookup(self, key):
        """The cached hint list for ``key`` or None; counts
        hits/misses on the calling thread's active collector."""
        from ..stats import current_stats

        st = current_stats()
        with self._lock:
            got = self._entries.get(key)
            if got is not None:
                self._entries.move_to_end(key)
        if got is None:
            if st is not None:
                st.plan_cache_misses += 1
            return None
        if st is not None:
            st.plan_cache_hits += 1
        return got[0]

    def store(self, key, record, budget: int) -> None:
        """Insert/refresh ``key``; evicts LRU entries past ``budget``
        bytes (evictions counted on the calling thread's collector)."""
        nbytes = _entry_nbytes(record)
        if nbytes > budget:
            return  # one oversized entry must not flush the whole cache
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (record, nbytes)
            self._bytes += nbytes
            while self._bytes > budget and self._entries:
                _, (_, nb) = self._entries.popitem(last=False)
                self._bytes -= nb
                evicted += 1
        if evicted:
            from ..obs.recorder import flight
            from ..stats import current_stats

            flight("plan_cache_evict", site="kernels.plancache",
                   evicted=evicted)
            st = current_stats()
            if st is not None:
                st.plan_cache_evictions += evicted

    def invalidate_fingerprint(self, fingerprint) -> None:
        """Drop every entry of one file identity (corruption seen)."""
        if fingerprint is None:
            return
        with self._lock:
            stale = [k for k in self._entries if k[0] == fingerprint]
            for k in stale:
                _, nb = self._entries.pop(k)
                self._bytes -= nb
        if stale:
            from ..obs.recorder import flight

            # an invalidation marks a corruption event — exactly the
            # kind of trailing context a post-mortem wants
            flight("plan_cache_invalidate", site="kernels.plancache",
                   entries=len(stale))

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    @property
    def nbytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)


_CACHE = PlanCache()


def plan_cache() -> PlanCache | None:
    """The process-wide cache when ``TPQ_PLAN_CACHE_MB`` enables it,
    else None (the hot path's single gate)."""
    return _CACHE if plan_cache_budget() > 0 else None


def invalidate_fingerprint(fingerprint) -> None:
    """Drop a file's cached plans (corruption/salvage event) — valid
    whether or not the cache is currently enabled."""
    _CACHE.invalidate_fingerprint(fingerprint)


def clear_plan_cache() -> None:
    """Drop everything (tests / explicit reset)."""
    _CACHE.clear()
