"""Device hybrid RLE/bit-packed decode: host run-table plan + device expand.

The sequential uvarint-chained run structure (SURVEY.md §7 "hard parts") is
resolved in a cheap host pass over the run *headers* only (a few bytes per
run); the values themselves are never touched on host.  The plan is:

* ``bp_words``: all bit-packed segments concatenated, staged as u32 words;
* ``run_ends``: cumulative output counts per run (searchsorted key);
* ``run_is_rle`` / ``run_value``: RLE runs' fill values;
* ``run_bp_start``: for BP runs, the value offset into the unpacked stream.

Device expansion is then fully parallel: unpack all BP segments in one
shot, and for every output slot pick either its RLE fill value or its
unpacked value via a vectorized ``searchsorted`` over run boundaries.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..varint import read_uvarint
from .bitunpack import pad_to_words, unpack_u32

__all__ = [
    "plan_hybrid", "pad_plan", "expand_hybrid", "expand_hybrid_core",
    "decode_hybrid_device", "decode_hybrid_device_padded", "HybridPlan",
]


class HybridPlan:
    """Host-built run table (static shapes per stream)."""

    __slots__ = (
        "bp_words", "run_ends", "run_is_rle", "run_value", "run_bp_start",
        "count", "width", "n_bp_values",
    )

    def __init__(self, bp_words, run_ends, run_is_rle, run_value,
                 run_bp_start, count, width, n_bp_values):
        self.bp_words = bp_words
        self.run_ends = run_ends
        self.run_is_rle = run_is_rle
        self.run_value = run_value
        self.run_bp_start = run_bp_start
        self.count = count
        self.width = width
        self.n_bp_values = n_bp_values


def plan_hybrid(data, count: int, width: int, pos: int = 0) -> HybridPlan:
    """Parse run headers into a run table (host, metadata-sized work)."""
    vbytes = (width + 7) // 8
    buf = data if isinstance(data, (bytes, bytearray, memoryview)) else bytes(data)
    ends = []
    is_rle = []
    values = []
    bp_starts = []
    bp_segments = []
    filled = 0
    n_bp = 0
    while filled < count:
        h, pos = read_uvarint(buf, pos)
        if h & 1:
            n = (h >> 1) * 8
            nbytes = (n * width + 7) // 8
            if pos + nbytes > len(buf):
                raise ValueError("truncated bit-packed run")
            bp_segments.append(np.frombuffer(buf, np.uint8, nbytes, pos))
            bp_starts.append(n_bp)
            values.append(0)
            is_rle.append(False)
            pos += nbytes
            take = min(n, count - filled)
            # the unpacked stream keeps the full n values; consumers index
            # through run_bp_start so padding values are never selected
            n_bp += n
            filled += take
        else:
            n = h >> 1
            if n == 0:
                raise ValueError("zero-length RLE run")
            if pos + vbytes > len(buf):
                raise ValueError("truncated RLE run value")
            v = int.from_bytes(buf[pos : pos + vbytes], "little")
            pos += vbytes
            values.append(v)
            is_rle.append(True)
            bp_starts.append(n_bp)
            take = min(n, count - filled)
            filled += take
        ends.append(filled)
    if not ends:
        ends, is_rle, values, bp_starts = [0], [True], [0], [0]
    if bp_segments:
        packed = np.concatenate(bp_segments)
    else:
        packed = np.zeros(0, dtype=np.uint8)
    bp_words = pad_to_words(packed, max(width, 1), max(n_bp, 1))
    return HybridPlan(
        bp_words=bp_words,
        run_ends=np.asarray(ends, dtype=np.int32),
        run_is_rle=np.asarray(is_rle, dtype=bool),
        run_value=np.asarray(values, dtype=np.uint32),
        run_bp_start=np.asarray(bp_starts, dtype=np.int32),
        count=count,
        width=width,
        n_bp_values=max(n_bp, 1),
    )


def expand_hybrid_core(bp_words, run_ends, run_is_rle, run_value,
                       run_bp_start, idx, width: int, n_bp: int) -> jax.Array:
    """Run expansion for an arbitrary set of output positions ``idx``.

    Pure traceable core shared by :func:`expand_hybrid`, the vmapped batch
    variant, and the shard_map sequence-parallel step (each shard passes
    its own slice of positions)."""
    unpacked = unpack_u32(bp_words, max(width, 1), n_bp)
    run = jnp.searchsorted(run_ends, idx, side="right").astype(jnp.int32)
    run = jnp.minimum(run, run_ends.shape[0] - 1)
    run_start = jnp.where(run > 0, run_ends[run - 1], 0)
    within = idx - run_start
    bp_pos = jnp.clip(run_bp_start[run] + within, 0, n_bp - 1)
    return jnp.where(run_is_rle[run], run_value[run], unpacked[bp_pos])


@functools.partial(jax.jit, static_argnames=("count", "width", "n_bp"))
def expand_hybrid(bp_words, run_ends, run_is_rle, run_value, run_bp_start,
                  count: int, width: int, n_bp: int) -> jax.Array:
    """Vectorized run expansion on device; returns (count,) u32."""
    if count == 0:
        return jnp.zeros((0,), dtype=jnp.uint32)
    idx = jnp.arange(count, dtype=jnp.int32)
    return expand_hybrid_core(bp_words, run_ends, run_is_rle, run_value,
                              run_bp_start, idx, width, n_bp)


def pad_plan(p: HybridPlan):
    """Pad one plan's dynamic dims (run count, bp count, output count) to
    power-of-two buckets so jitted expands cache on buckets, not exact
    per-page sizes.  Returns (staged array tuple, cnt, width, n_bp)."""
    from .decode import bucket

    cnt = bucket(p.count)
    R = bucket(len(p.run_ends))
    n_bp = bucket(p.n_bp_values)
    n_blocks = (n_bp + 31) // 32
    w = max(p.width, 1)
    bp_words = np.zeros((n_blocks, w), dtype=np.uint32)
    bp_words[: p.bp_words.shape[0], : p.bp_words.shape[1]] = p.bp_words
    # padding runs end at cnt (monotone, never selected for idx < count)
    run_ends = np.full(R, cnt, dtype=np.int32)
    run_ends[: len(p.run_ends)] = p.run_ends
    run_is_rle = np.ones(R, dtype=bool)
    run_is_rle[: len(p.run_is_rle)] = p.run_is_rle
    run_value = np.zeros(R, dtype=np.uint32)
    run_value[: len(p.run_value)] = p.run_value
    run_bp_start = np.zeros(R, dtype=np.int32)
    run_bp_start[: len(p.run_bp_start)] = p.run_bp_start
    return (bp_words, run_ends, run_is_rle, run_value,
            run_bp_start), cnt, p.width, n_bp


def decode_hybrid_device_padded(data, count: int, width: int, pos: int = 0):
    """Host plan + device expand, returning the bucket-padded output
    (shape (bucket(count),), tail zeros) — callers that feed another
    padded kernel can skip the slice/re-pad round trip."""
    args, cnt, w, n_bp = pad_plan(plan_hybrid(data, count, width, pos))
    return expand_hybrid(*(jnp.asarray(a) for a in args), cnt, w, n_bp)


def decode_hybrid_device(data, count: int, width: int, pos: int = 0):
    """End-to-end: host plan + device expand (convenience wrapper)."""
    return decode_hybrid_device_padded(data, count, width, pos)[:count]
