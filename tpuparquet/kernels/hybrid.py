"""Device hybrid RLE/bit-packed decode: host run-table plan + device expand.

The sequential uvarint-chained run structure (SURVEY.md §7 "hard parts") is
resolved in a cheap host pass over the run *headers* only (a few bytes per
run); the values themselves are never touched on host.  The plan is:

* ``bp_words``: all bit-packed segments concatenated, staged as u32 words;
* ``run_ends``: cumulative output counts per run (searchsorted key);
* ``run_is_rle`` / ``run_value``: RLE runs' fill values;
* ``run_bp_start``: for BP runs, the value offset into the unpacked stream.

Device expansion is then fully parallel: unpack all BP segments in one
shot, and for every output slot pick either its RLE fill value or its
unpacked value via a vectorized ``searchsorted`` over run boundaries.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .bitunpack import pad_to_words, unpack_u32

__all__ = [
    "plan_hybrid", "plan_from_scan", "count_eq_scan", "max_scan_value",
    "pad_plan",
    "expand_hybrid", "expand_hybrid_core", "expand_plan_padded",
    "decode_hybrid_device", "decode_hybrid_device_padded", "HybridPlan",
]


class HybridPlan:
    """Host-built run table (static shapes per stream)."""

    __slots__ = (
        "bp_words", "run_ends", "run_is_rle", "run_value", "run_bp_start",
        "count", "width", "n_bp_values",
    )

    def __init__(self, bp_words, run_ends, run_is_rle, run_value,
                 run_bp_start, count, width, n_bp_values):
        self.bp_words = bp_words
        self.run_ends = run_ends
        self.run_is_rle = run_is_rle
        self.run_value = run_value
        self.run_bp_start = run_bp_start
        self.count = count
        self.width = width
        self.n_bp_values = n_bp_values


def plan_hybrid(data, count: int, width: int, pos: int = 0) -> HybridPlan:
    """Parse run headers into a run table (host, metadata-sized work).

    Delegates the scan to the shared (native-C-accelerated) pass-1
    scanner and stages the bit-packed bytes as padded u32 words."""
    from ..cpu.hybrid import scan_hybrid

    return plan_from_scan(scan_hybrid(data, count, width, pos),
                          count, width)


def plan_from_scan(scan, count: int, width: int) -> HybridPlan:
    """Build a device plan from a :func:`scan_hybrid` result (lets the
    caller reuse one scan for both the plan and host-side counting)."""
    ends, is_rle, values, bp_starts, bp_bytes, n_bp, _ = scan
    if len(ends) == 0:
        ends = np.zeros(1, dtype=np.int32)
        is_rle = np.ones(1, dtype=bool)
        values = np.zeros(1, dtype=np.uint32)
        bp_starts = np.zeros(1, dtype=np.int32)
    bp_words = pad_to_words(np.asarray(bp_bytes, dtype=np.uint8),
                            max(width, 1), max(n_bp, 1))
    return HybridPlan(
        bp_words=bp_words,
        run_ends=np.asarray(ends, dtype=np.int32),
        run_is_rle=np.asarray(is_rle, dtype=bool),
        run_value=np.asarray(values, dtype=np.uint32),
        run_bp_start=np.asarray(bp_starts, dtype=np.int32),
        count=count,
        width=width,
        n_bp_values=max(n_bp, 1),
    )


def _bp_lane_stats(scan, width: int, target: int):
    """(max value | None, count == target) over a scan's consumed
    bit-packed lanes.  One native C pass when available; numpy unpack +
    active-lane mask otherwise.  Lanes in per-run 8-group padding are
    excluded either way."""
    ends, is_rle, _, bp_starts, bp_bytes, n_bp, _pos = scan
    lens = np.diff(ends, prepend=np.int32(0))
    bp = ~is_rle
    if not bp.any() or not n_bp:
        return None, 0
    from ..native import hybrid_native

    nat = hybrid_native()
    if nat is not None:
        try:
            return nat.bp_stats(bp_bytes, width, bp_starts[bp], lens[bp],
                                target)
        except RuntimeError:  # stale .so without tpq_bp_stats
            pass
    # record the degradation: this fallback unpacks the whole stream in
    # numpy, so perf quietly regresses with no functional symptom
    from ..stats import current_stats

    _st = current_stats()
    if _st is not None:
        _st.native_fallbacks += 1
    from ..cpu.bitpack import unpack

    unpacked = unpack(bp_bytes, n_bp, width)
    delta = np.zeros(n_bp + 1, dtype=np.int64)
    starts = bp_starts[bp].astype(np.int64)
    np.add.at(delta, starts, 1)
    np.add.at(delta, starts + lens[bp], -1)
    active = np.cumsum(delta[:-1]) > 0
    if not active.any():
        return None, 0
    return (int(unpacked[active].max()),
            int(((unpacked == target) & active).sum()))


def count_eq_scan(scan, width: int, target: int,
                  validate_max: bool = False) -> int:
    """Count occurrences of ``target`` from a scan's run table without a
    full expand: RLE runs are arithmetic, bit-packed segments get one
    native C pass.  Used to count non-null values (def == max_def)
    without a device sync or a second decode.

    ``validate_max`` additionally rejects any level above ``target``
    (the level-range check of ``cpu/levels._check``; values above
    max_def would otherwise silently read as null)."""
    ends, is_rle, values = scan[0], scan[1], scan[2]
    if len(ends) == 0:
        return 0
    lens = np.diff(ends, prepend=np.int32(0))
    live = lens > 0
    if validate_max and bool((values[is_rle & live] > target).any()):
        raise ValueError(
            f"level value {int(values[is_rle & live].max())} exceeds "
            f"max level {target}"
        )
    cnt = int(lens[is_rle & (values == target)].sum())
    bp_max, bp_cnt = _bp_lane_stats(scan, width, target)
    if bp_max is not None:
        if validate_max and bp_max > target:
            raise ValueError(
                f"level value {bp_max} exceeds max level {target}"
            )
        cnt += bp_cnt
    return cnt


def max_scan_value(scan, width: int) -> int:
    """Max decoded value across a scan's live runs (RLE fills + active
    bit-packed lanes), without a device round-trip.  -1 when empty.

    Used to validate dictionary indices host-side: the device gather
    clamps indices (padding lanes must stay in range), which would turn
    a corrupt file's out-of-range index into a silent wrong value."""
    ends, is_rle, values = scan[0], scan[1], scan[2]
    if len(ends) == 0:
        return -1
    lens = np.diff(ends, prepend=np.int32(0))
    mx = -1
    rle_live = is_rle & (lens > 0)
    if rle_live.any():
        mx = int(values[rle_live].max())
    bp_max, _ = _bp_lane_stats(scan, width, 0)
    if bp_max is not None:
        mx = max(mx, bp_max)
    return mx


def expand_hybrid_core(bp_words, run_ends, run_is_rle, run_value,
                       run_bp_start, idx, width: int, n_bp: int) -> jax.Array:
    """Run expansion for an arbitrary set of output positions ``idx``.

    Pure traceable core shared by :func:`expand_hybrid`, the vmapped batch
    variant, and the shard_map sequence-parallel step (each shard passes
    its own slice of positions)."""
    unpacked = unpack_u32(bp_words, max(width, 1), n_bp)
    run = jnp.searchsorted(run_ends, idx, side="right").astype(jnp.int32)
    run = jnp.minimum(run, run_ends.shape[0] - 1)
    run_start = jnp.where(run > 0, run_ends[run - 1], 0)
    within = idx - run_start
    bp_pos = jnp.clip(run_bp_start[run] + within, 0, n_bp - 1)
    return jnp.where(run_is_rle[run], run_value[run], unpacked[bp_pos])


@functools.partial(jax.jit, static_argnames=("count", "width", "n_bp"))
def expand_hybrid(bp_words, run_ends, run_is_rle, run_value, run_bp_start,
                  count: int, width: int, n_bp: int) -> jax.Array:
    """Vectorized run expansion on device; returns (count,) u32."""
    if count == 0:
        return jnp.zeros((0,), dtype=jnp.uint32)
    idx = jnp.arange(count, dtype=jnp.int32)
    return expand_hybrid_core(bp_words, run_ends, run_is_rle, run_value,
                              run_bp_start, idx, width, n_bp)


def pad_plan(p: HybridPlan):
    """Pad one plan's dynamic dims (run count, bp count, output count) to
    power-of-two buckets so jitted expands cache on buckets, not exact
    per-page sizes.  Returns (staged array tuple, cnt, width, n_bp)."""
    from .decode import bucket

    cnt = bucket(p.count)
    R = bucket(len(p.run_ends))
    n_bp = bucket(p.n_bp_values)
    n_blocks = (n_bp + 31) // 32
    w = max(p.width, 1)
    bp_words = np.zeros((n_blocks, w), dtype=np.uint32)
    bp_words[: p.bp_words.shape[0], : p.bp_words.shape[1]] = p.bp_words
    # padding runs end at cnt (monotone, never selected for idx < count)
    run_ends = np.full(R, cnt, dtype=np.int32)
    run_ends[: len(p.run_ends)] = p.run_ends
    run_is_rle = np.ones(R, dtype=bool)
    run_is_rle[: len(p.run_is_rle)] = p.run_is_rle
    run_value = np.zeros(R, dtype=np.uint32)
    run_value[: len(p.run_value)] = p.run_value
    run_bp_start = np.zeros(R, dtype=np.int32)
    run_bp_start[: len(p.run_bp_start)] = p.run_bp_start
    # flat bp words, same as pack_plan (2-D tiles to 128 lanes on TPU)
    return (bp_words.reshape(-1), run_ends, run_is_rle, run_value,
            run_bp_start), cnt, p.width, n_bp


def pack_plan(p: HybridPlan):
    """Pad like :func:`pad_plan` but pack the four run-table columns into
    ONE (4, R) u32 array — halving the per-stream transfer count (each
    host->device array has fixed per-array overhead on a remote TPU).

    Rows: 0=run_ends, 1=is_rle, 2=value, 3=bp_start.  Returns
    ((bp_words, table), cnt, width, n_bp)."""
    from .decode import bucket

    cnt = bucket(p.count)
    R = bucket(len(p.run_ends))
    n_bp = bucket(p.n_bp_values)
    n_blocks = (n_bp + 31) // 32
    w = max(p.width, 1)
    bp_words = np.zeros((n_blocks, w), dtype=np.uint32)
    bp_words[: p.bp_words.shape[0], : p.bp_words.shape[1]] = p.bp_words
    table = np.zeros((4, R), dtype=np.uint32)
    table[0, :] = cnt  # padding runs end at cnt (monotone)
    table[0, : len(p.run_ends)] = p.run_ends.astype(np.uint32)
    table[1, :] = 1    # padding runs are RLE of 0
    table[1, : len(p.run_is_rle)] = p.run_is_rle.astype(np.uint32)
    table[2, : len(p.run_value)] = p.run_value
    table[3, : len(p.run_bp_start)] = p.run_bp_start.astype(np.uint32)
    # bp words ship FLAT: a (n_blocks, w) u32 device buffer tiles its
    # <=32-wide minor dim to 128 lanes on TPU (128/w x transient HBM);
    # the unpack kernels reshape inside their jit, where it fuses
    return (bp_words.reshape(-1), table), cnt, p.width, n_bp


def expand_plan_padded(p: HybridPlan):
    """Device expand of an existing plan, bucket-padded output."""
    args, cnt, w, n_bp = pad_plan(p)
    return expand_hybrid(*(jnp.asarray(a) for a in args), cnt, w, n_bp)


def decode_hybrid_device_padded(data, count: int, width: int, pos: int = 0):
    """Host plan + device expand, returning the bucket-padded output
    (shape (bucket(count),), tail zeros) — callers that feed another
    padded kernel can skip the slice/re-pad round trip."""
    return expand_plan_padded(plan_hybrid(data, count, width, pos))


def decode_hybrid_device(data, count: int, width: int, pos: int = 0):
    """End-to-end: host plan + device expand (convenience wrapper)."""
    return decode_hybrid_device_padded(data, count, width, pos)[:count]


def plan_stream_args(scan, count: int, width: int, expanded=None):
    """((bp_words, table), cnt, nbp, single) staging plan for one hybrid
    stream — the single decision point for how a level/index stream goes
    on the wire.

    Mixed-run streams (random validity masks, irregular dict indices)
    can carry run tables of 16 bytes/run that dwarf the packed values
    themselves (measured: a 6 KB def-level stream shipping a 262 KB
    table).  When the stream's TOTAL wire (table + its bit-packed
    segments, bucket-padded as shipped) exceeds a plain bit-packing of
    all values, the host expands the runs (vectorized pass 2; pass
    ``expanded`` to reuse a caller's expansion) and re-packs them as
    ONE bit-packed run: a minimal table ships and the device expansion
    degenerates to a pure tiled unpack (``single=True``)."""
    from .decode import bucket

    def bp_wire(n_vals: int) -> int:
        return ((bucket(max(n_vals, 1)) + 31) // 32) * 4 * width

    single = single_bp_scan(scan)
    if not single and width and count >= 1024:
        n_bp = int(scan[5])
        old_wire = 16 * bucket(max(len(scan[0]), 1)) + (
            bp_wire(n_bp) if n_bp else 0)
        new_wire = 16 * bucket(1) + bp_wire(count)
        if old_wire > new_wire:
            packed = None
            if expanded is None:
                from ..native import pack_native

                nat = pack_native()
                if nat is not None:
                    # fused run-table -> packed bits: no expanded
                    # intermediate, one C pass
                    packed = nat.hybrid_repack(
                        scan[0], scan[1], scan[2], scan[3], scan[4],
                        scan[5], count, width)
            if packed is None:
                from ..cpu.bitpack import pack
                from ..cpu.hybrid import expand_scan

                vals = (expanded if expanded is not None
                        else expand_scan(*scan[:6], count, width))
                packed = np.frombuffer(pack(vals[:count], width),
                                       dtype=np.uint8)
            scan = (np.array([count], dtype=np.int32),
                    np.zeros(1, dtype=bool),
                    np.zeros(1, dtype=np.uint32),
                    np.zeros(1, dtype=np.int32),
                    packed, count, scan[6])
            single = True
    args, cnt, _, nbp = pack_plan(plan_from_scan(scan, count, width))
    return args, cnt, nbp, single


def single_bp_scan(scan) -> bool:
    """True when a scan is exactly one bit-packed run — expansion then
    degenerates to a pure tiled bit-unpack (no run search), which the
    fused kernels run as the Pallas unpack on TPU."""
    ends, is_rle = scan[0], scan[1]
    return len(ends) == 1 and not bool(is_rle[0])
