"""Device value-decode kernels: PLAIN staging, levels→validity, dictionary
gather (fixed and variable width), BYTE_STREAM_SPLIT, and
DELTA_BINARY_PACKED int32/int64.

All kernels follow the same shape discipline: hosts stage *padded,
fixed-shape* buffers (page bytes as u32 words, run/plan tables as arrays)
and devices run pure vectorized expansion under ``jit`` — no
data-dependent Python control flow crosses the boundary (SURVEY.md §7).
Dynamic output sizes (variable-length gathers) are padded to power-of-two
buckets so XLA compiles one kernel per bucket, not per page.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .bitunpack import pad_to_words, unpack_u32

__all__ = [
    "stage_u32",
    "bss_to_lanes",
    "plain_fixed_to_lanes",
    "levels_to_validity",
    "scatter_to_dense",
    "dict_gather_fixed",
    "dict_gather_bytes",
    "plan_delta_i32",
    "expand_delta_i32",
    "plan_delta_i64",
    "expand_delta_i64",
    "bucket",
]


def bucket(n: int) -> int:
    """Round up to a power-of-two bucket (min 32) to bound recompilation."""
    b = 32
    while b < n:
        b <<= 1
    return b


def stage_u32(data, n_words: int) -> np.ndarray:
    """Host staging: raw little-endian bytes -> padded u32 word array."""
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(
        data, np.ndarray
    ) else data
    need = n_words * 4
    if len(buf) < need:
        out = np.zeros(need, dtype=np.uint8)
        out[: len(buf)] = buf[:need]
        buf = out
    return buf[:need].view("<u4")


@functools.partial(jax.jit, static_argnames=("n_words",))
def u8_to_u32_words(b: jax.Array, n_words: int):
    """Device-resident little-endian byte stream -> (n_words,) u32.

    The device twin of :func:`stage_u32` for bytes that never visit the
    host (e.g. the device snappy decompressor's output)."""
    w = b[: n_words * 4].astype(jnp.uint32).reshape(-1, 4)
    return w[:, 0] | (w[:, 1] << 8) | (w[:, 2] << 16) | (w[:, 3] << 24)


@functools.partial(jax.jit, static_argnames=("n_words",))
def u8_to_u32_words_at(b: jax.Array, off, n_words: int):
    """Like :func:`u8_to_u32_words` but reading from byte offset ``off``
    (a traced scalar, so one compiled kernel serves every page of a
    chunk regardless of how many level bytes precede its values
    segment)."""
    w = jax.lax.dynamic_slice(b, (off,), (n_words * 4,))
    w = w.astype(jnp.uint32).reshape(-1, 4)
    return w[:, 0] | (w[:, 1] << 8) | (w[:, 2] << 16) | (w[:, 3] << 24)


@functools.partial(jax.jit, static_argnames=("count", "k", "lanes"))
def bss_to_lanes(raw: jax.Array, count: int, k: int, lanes: int):
    """BYTE_STREAM_SPLIT decode on device: ``k`` byte streams of
    ``count`` bytes each -> flat (count*lanes,) u32 little-endian lane
    words.  The scatter of value bytes across streams
    (``cpu/bss.py``) inverts to one transpose — ideal device work:
    no sequential structure at all."""
    streams = raw[: k * count].reshape(k, count)
    rows = streams.T                                  # (count, k) u8
    if k != lanes * 4:
        rows = jnp.pad(rows, ((0, 0), (0, lanes * 4 - k)))
    b = rows.reshape(count, lanes, 4).astype(jnp.uint32)
    words = (b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16)
             | (b[..., 3] << 24))
    return words.reshape(-1)


@functools.partial(jax.jit, static_argnames=("count", "type_length"))
def flba_bytes_to_lanes(raw: jax.Array, count: int, type_length: int):
    """Device-resident FLBA byte rows -> flat (count*lanes,) u32 lane
    words (rows zero-padded to whole little-endian u32 lanes — the
    DeviceColumn FLBA layout of ``_stage_byte_rows_np``).  Lets a
    device expansion (e.g. DELTA_BYTE_ARRAY front coding) feed a fixed
    column without a host round trip."""
    L = type_length
    lanes = (L + 3) // 4
    rows = raw[: count * L].reshape(count, L)
    if L != lanes * 4:
        rows = jnp.pad(rows, ((0, 0), (0, lanes * 4 - L)))
    b = rows.reshape(count, lanes, 4).astype(jnp.uint32)
    words = (b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16)
             | (b[..., 3] << 24))
    return words.reshape(-1)


def _rle_expand(ends: jax.Array, vals: jax.Array, start: int, n_runs: int,
                count: int):
    """Run table slice -> per-position values (searchsorted expand)."""
    e = ends[start : start + n_runs]
    i = jnp.arange(count, dtype=jnp.int32)
    idx = jnp.searchsorted(e, i, side="right").astype(jnp.int32)
    idx = jnp.minimum(idx, n_runs - 1)
    return vals[start + idx]


@functools.partial(jax.jit, static_argnames=("spec", "count", "lanes"))
def planes_to_words(raw32: jax.Array, rle32_ends: jax.Array,
                    rle32_vals: jax.Array, raw8: jax.Array,
                    rle8_ends: jax.Array, rle8_vals: jax.Array,
                    spec: tuple, count: int, lanes: int):
    """Lane/byte-plane wire transport -> flat u32 lane words.

    The host ships each of the value's u32 lanes one of three ways —
    whole-lane run-length coding (``("rle32", start, n_runs)``: numeric
    data's high words are runs), raw (``("raw32", slab)``), or
    descended to its four byte planes (``("bytes", e0, e1, e2, e3)``
    with per-plane ``("raw8", slab)`` / ``("rle8", start, n_runs)``
    entries: catches constant upper bytes INSIDE an otherwise-random
    lane, e.g. values < 2^16 in an int64).  Only genuinely random bytes
    pay full wire; reconstruction (searchsorted expands + shift
    combine) is pure parallel device work."""
    words = []
    for entry in spec:
        kind = entry[0]
        if kind == "raw32":
            j = entry[1]
            words.append(raw32[j * count : (j + 1) * count])
        elif kind == "rle32":
            words.append(_rle_expand(rle32_ends, rle32_vals,
                                     entry[1], entry[2], count))
        else:  # "bytes": four byte-plane sub-entries
            b = []
            for sub in entry[1:]:
                if sub[0] == "raw8":
                    j = sub[1]
                    b.append(raw8[j * count : (j + 1) * count]
                             .astype(jnp.uint32))
                else:
                    b.append(_rle_expand(rle8_ends, rle8_vals,
                                         sub[1], sub[2], count)
                             .astype(jnp.uint32))
            words.append(b[0] | (b[1] << 8) | (b[2] << 16)
                         | (b[3] << 24))
    if lanes == 1:
        return words[0]
    return jnp.stack(words, axis=1).reshape(-1)


@functools.partial(jax.jit, static_argnames=("count", "lanes"))
def plain_fixed_to_lanes(words: jax.Array, count: int, lanes: int):
    """PLAIN fixed-width values staged as u32 words -> flat u32 lanes.

    lanes=1: int32/float32; lanes=2: int64/double (lo, hi); lanes=3: int96.
    The 'decode' of PLAIN on device is a reinterpret — the point is that
    the bytes are already in HBM and never round-trip through host.

    Value buffers stay FLAT 1-D at every jit boundary: TPU tiles a 2-D
    ``u32[n, lanes]`` output as T(8,128), padding the minor dim to 128
    lanes — 64x HBM waste for int64, 128x for int32 (measured: a 400 MB
    ``u32[50M,2]`` column would allocate 25.6 GB and OOM the chip)."""
    return words[: count * lanes]


@functools.partial(jax.jit, static_argnames=("max_def",))
def levels_to_validity(def_levels: jax.Array, max_def: int):
    """Def levels -> (validity mask, packed-value position per slot).

    The fused kernel of SURVEY §2.8: mask = (def == max_def), and
    positions[i] = how many non-null values precede slot i — the gather
    index used to inflate packed values to record slots."""
    mask = def_levels == jnp.int32(max_def)
    positions = jnp.cumsum(mask.astype(jnp.int32)) - 1
    return mask, jnp.maximum(positions, 0)


@functools.partial(jax.jit, static_argnames=("lanes",))
def scatter_to_dense(packed: jax.Array, mask: jax.Array,
                     positions: jax.Array, lanes: int = 1):
    """Inflate packed non-null values to one-per-slot dense form (null
    slots get 0).  ``packed`` is flat 1-D with ``lanes`` u32 words per
    value (the DeviceColumn layout); 2-D (n, lanes) inputs are also
    accepted for synthetic callers (output stays 2-D then)."""
    if packed.shape[0] == 0:
        # all slots null (zero packed values): nothing to gather — an
        # empty-buffer gather is out-of-range at any index
        n = mask.shape[0]
        shape = ((n,) + packed.shape[1:] if packed.ndim > 1
                 else (n * lanes,))
        return jnp.zeros(shape, dtype=packed.dtype)
    if packed.ndim > 1:
        gathered = packed[positions]
        return jnp.where(mask[:, None], gathered,
                         jnp.zeros_like(gathered))
    if lanes == 1:
        return jnp.where(mask, packed[positions],
                         jnp.zeros((), dtype=packed.dtype))
    m = jnp.repeat(mask, lanes)
    return jnp.where(m, packed[_flat_lane_indices(positions, lanes)],
                     jnp.zeros((), dtype=packed.dtype))


def _flat_lane_indices(idx, lanes: int):
    """Value indices -> flat word indices in a (n*lanes,) lane buffer."""
    return (idx[:, None] * lanes
            + jnp.arange(lanes, dtype=idx.dtype)).reshape(-1)


@functools.partial(jax.jit, static_argnames=("lanes",))
def dict_gather_fixed(dictionary: jax.Array, indices: jax.Array,
                      lanes: int = 1):
    """Fixed-width dictionary gather over a FLAT (D*lanes,) u32 dict."""
    return _dict_gather_flat(dictionary, indices, lanes)


def _dict_gather_flat(dictionary, indices, lanes: int):
    if lanes == 1:
        return dictionary[indices]
    return dictionary[_flat_lane_indices(indices, lanes)]


# ----------------------------------------------------------------------
# Fused per-page kernels: one dispatch per data page.  Decoding a page is
# index-expand + gather (+ level expand); issuing them as one jit lets
# XLA fuse everything and — more importantly on a remote-attached TPU —
# collapses N dispatches into one.
# ----------------------------------------------------------------------

def _expand_core(bp, ends, rle, val, start, cnt: int, w: int, nbp: int):
    from .hybrid import expand_hybrid_core

    idx = jnp.arange(cnt, dtype=jnp.int32)
    return expand_hybrid_core(bp, ends, rle, val, start, idx, w, nbp)


def _expand_tbl(bp, table, cnt: int, w: int, nbp: int):
    """Expand from a packed (4, R) u32 run table (see hybrid.pack_plan)."""
    return _expand_core(
        bp, table[0].astype(jnp.int32), table[1] != 0, table[2],
        table[3].astype(jnp.int32), cnt, w, nbp,
    )


def _expand_stream(bp, table, cnt: int, w: int, nbp: int, single: bool):
    """Stream expansion with a static fast path: a single bit-packed run
    (what our encoder and most writers emit for levels and dict indices)
    needs no run search at all — it is a pure tiled bit-unpack.
    ``single`` is decided on host and is part of the jit key.

    The Pallas formulation of this unpack (``bitunpack.unpack_u32_pallas``,
    with the documented Mosaic width>=17 straddle-shift workaround) was
    A/B'd jitted on TPU v5e across widths 1..32 and lost or tied XLA at
    every width, so the production path is XLA-only; the kernel remains
    validated by tests (interpret mode) and measurable via
    ``tools/bench_pallas.py`` should a future Mosaic change the verdict."""
    if single and w:
        from .bitunpack import unpack_u32

        return unpack_u32(bp, w, cnt)
    return _expand_tbl(bp, table, cnt, w, nbp)


@functools.partial(jax.jit, static_argnames=("cnt", "w", "nbp", "single"))
def expand_tbl(bp, table, cnt: int, w: int, nbp: int,
               single: bool = False):
    return _expand_stream(bp, table, cnt, w, nbp, single)


@functools.partial(jax.jit, static_argnames=(
    "dcnt", "dw", "dnbp", "icnt", "iw", "inbp", "lanes", "dsingle",
    "isingle"))
def page_dict_fixed_levels_tbl(dictionary, d_bp, d_tbl, i_bp, i_tbl,
                               dcnt: int, dw: int, dnbp: int,
                               icnt: int, iw: int, inbp: int,
                               lanes: int = 1,
                               dsingle: bool = False,
                               isingle: bool = False):
    """Fused dict-page decode from packed run tables (one dispatch).
    ``dictionary`` is flat (D*lanes,) u32; returns flat values."""
    dl = _expand_stream(d_bp, d_tbl, dcnt, dw, dnbp,
                        dsingle).astype(jnp.int32)
    idx = _expand_stream(i_bp, i_tbl, icnt, iw, inbp,
                         isingle).astype(jnp.int32)
    n_dict = dictionary.shape[0] // lanes
    vals = _dict_gather_flat(dictionary, jnp.minimum(idx, n_dict - 1),
                             lanes)
    return vals, dl


@functools.partial(jax.jit, static_argnames=("icnt", "iw", "inbp", "lanes",
                                             "isingle"))
def page_dict_fixed_tbl(dictionary, i_bp, i_tbl,
                        icnt: int, iw: int, inbp: int, lanes: int = 1,
                        isingle: bool = False):
    idx = _expand_stream(i_bp, i_tbl, icnt, iw, inbp,
                         isingle).astype(jnp.int32)
    n_dict = dictionary.shape[0] // lanes
    return _dict_gather_flat(dictionary, jnp.minimum(idx, n_dict - 1),
                             lanes)


@functools.partial(jax.jit, static_argnames=(
    "count", "lanes", "dcnt", "dw", "dnbp", "dsingle"))
def page_plain_fixed_levels_tbl(words, d_bp, d_tbl, count: int, lanes: int,
                                dcnt: int, dw: int, dnbp: int,
                                dsingle: bool = False):
    dl = _expand_stream(d_bp, d_tbl, dcnt, dw, dnbp,
                        dsingle).astype(jnp.int32)
    return words[: count * lanes], dl


@functools.partial(jax.jit, static_argnames=(
    "icnt", "iw", "inbp", "total_bytes", "has_idx", "isingle"))
def page_dict_bytes_tbl(dict_offsets, dict_data, i_bp, i_tbl, non_null,
                        icnt: int, iw: int, inbp: int, total_bytes: int,
                        has_idx: bool = True, isingle: bool = False):
    """Fused dict BYTE_ARRAY page decode: expand indices, derive the
    output offsets ON DEVICE (value lengths are just the dictionary
    offset diffs; a masked cumsum rebuilds the padded offset table the
    gather needs), then the byte-granular gather.  Shipping the offsets
    cost 4 bytes per value — more wire than the dict indices themselves
    for short-string columns; now only the run tables ship."""
    if has_idx:
        idx = _expand_stream(i_bp, i_tbl, icnt, iw, inbp,
                             isingle).astype(jnp.int32)
    else:
        idx = jnp.zeros((icnt,), jnp.int32)
    n_dict = dict_offsets.shape[0] - 1
    idx = jnp.clip(idx, 0, max(n_dict - 1, 0))
    lens = dict_offsets[1:] - dict_offsets[:-1]
    valid = jnp.arange(icnt, dtype=jnp.int32) < non_null
    contrib = jnp.where(valid, lens[idx], 0)
    out_offsets = jnp.concatenate([
        jnp.zeros((1,), dict_offsets.dtype),
        jnp.cumsum(contrib).astype(dict_offsets.dtype),
    ])
    return dict_gather_bytes(dict_offsets, dict_data, idx, out_offsets,
                             total_bytes)


@functools.partial(jax.jit, static_argnames=("total_bytes",))
def plain_bytes_from_blob(blob: jax.Array, out_offsets: jax.Array, pos,
                          total_bytes: int):
    """PLAIN BYTE_ARRAY values gathered out of a device-resident page
    blob (e.g. the snappy expansion), skipping each value's 4-byte
    length prefix: value ``v``'s bytes start at
    ``pos + out_offsets[v] + 4*(v+1)`` in the blob — pure arithmetic
    from the output offsets, no extra source table on the wire."""
    if blob.shape[0] == 0:
        return jnp.zeros((total_bytes,), dtype=jnp.uint8)
    b = jnp.arange(total_bytes, dtype=jnp.int32)
    val = jnp.searchsorted(out_offsets[1:], b, side="right").astype(
        jnp.int32)
    val = jnp.minimum(val, out_offsets.shape[0] - 2)
    src = pos + out_offsets[val] + 4 * (val + 1) + (b - out_offsets[val])
    src = jnp.clip(src, 0, blob.shape[0] - 1)
    return blob[src]


@functools.partial(jax.jit, static_argnames=("total_bytes",))
def dict_gather_bytes(dict_offsets: jax.Array, dict_data: jax.Array,
                      indices: jax.Array, out_offsets: jax.Array,
                      total_bytes: int):
    """Variable-length dictionary gather -> (out_offsets, out_data).

    For every output byte position, locate its value via searchsorted over
    the output offsets, then its source byte in the dictionary blob —
    the device analogue of the reference's per-value dict gather
    (``type_dict.go:39-59``), vectorized at byte granularity.

    A dictionary of all-empty strings has a zero-length blob (legal:
    ``type_bytearray.go:24-55`` decodes it with no special case); every
    gathered value is empty, so the output is pure padding — a gather
    over ``uint8[0]`` would be out of range, so short-circuit it."""
    if dict_data.shape[0] == 0:
        return jnp.zeros((total_bytes,), dtype=dict_data.dtype)
    b = jnp.arange(total_bytes, dtype=jnp.int32)
    val = jnp.searchsorted(out_offsets[1:], b, side="right").astype(jnp.int32)
    val = jnp.minimum(val, indices.shape[0] - 1)
    within = b - out_offsets[val]
    src = dict_offsets[indices[val]] + within
    src = jnp.clip(src, 0, dict_data.shape[0] - 1)
    return dict_data[src]


# ----------------------------------------------------------------------
# DELTA_BINARY_PACKED (int32) — host plan + device expand
# ----------------------------------------------------------------------

class DeltaPlan:
    __slots__ = (
        # list of 7-tuples (width, words, starts, takes,
        # n_vals, start, n_take); starts/takes are None for a
        # contiguous group, whose deltas land in the destination slice
        # [start, start + n_take) (the common single-width stream) —
        # otherwise per-MINIBLOCK scatter starts/take counts that the
        # device expands into the per-value grid (_scatter_grid)
        "groups",
        # per-BLOCK min_delta as u32 (lo, hi) lanes — the device repeats
        # them by block_size; shipping the per-delta expansion would be
        # 8 wire bytes per value (more than the raw column)
        "md_lo", "md_hi",
        "block_size", "first", "total",
    )

    def __init__(self, groups, md_lo, md_hi, block_size, first, total):
        self.groups = groups
        self.md_lo = md_lo
        self.md_hi = md_hi
        self.block_size = block_size
        self.first = first
        self.total = total


def _plan_delta(data, pos: int, max_width: int) -> DeltaPlan:
    """Parse DELTA_BINARY_PACKED headers; group miniblock payloads by bit
    width so the device unpacks each width class in one static-shape
    call.  Shared by the 32- and 64-bit planners (``max_width`` is the
    column's physical width — a wider miniblock is malformed).

    The structure pass (validation + per-miniblock bookkeeping) is the
    CPU oracle's own ``scan_delta_structure`` — one implementation of
    the parsing rules for both paths."""
    from ..cpu.delta import scan_delta_structure

    st = scan_delta_structure(data, pos, max_width=max_width)
    mb_size = st.mb_size
    buf = (data if isinstance(data, np.ndarray)
           else np.frombuffer(data, dtype=np.uint8))
    md_u = np.asarray(st.md_blocks, dtype=np.int64).view(np.uint64)
    md_lo = (md_u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    md_hi = (md_u >> np.uint64(32)).astype(np.uint32)
    groups = []
    for w, src_contig, p_w, s_w, t_w, dst_contig in st.grouped():
        nbytes = mb_size * w // 8
        k = len(p_w)
        if src_contig:
            packed = buf[p_w[0] : p_w[0] + nbytes * k]
        else:
            from ..native import delta_native

            nat = delta_native()
            packed = (nat.gather_segments(buf, p_w, nbytes)
                      if nat is not None else None)
            if packed is None:  # one Python slice per miniblock
                packed = np.concatenate(
                    [buf[p : p + nbytes] for p in p_w])
        n_vals = mb_size * k
        # flat: a 2-D (n_blocks, w) device buffer tiles to 128 lanes
        words = pad_to_words(packed, w, n_vals).reshape(-1)
        if dst_contig:
            # contiguous destination slice: only the globally-last
            # miniblock can be partial.  positions/keep stay None and
            # the expanders use a cheap dynamic-slice update.
            groups.append((w, words, None, None, n_vals,
                           int(s_w[0]), int(t_w.sum())))
        else:
            # scattered destinations ship per-MINIBLOCK starts/takes
            # (8 bytes each); the device rebuilds the per-value scatter
            # grid — per-value position arrays would cost more wire
            # than the packed deltas themselves
            groups.append((w, words, s_w.astype(np.int32),
                           t_w.astype(np.int32), n_vals, 0, 0))
    return DeltaPlan(groups, md_lo, md_hi, st.block_size, st.first,
                     st.total)


def plan_delta_i32(data, pos: int = 0) -> DeltaPlan:
    return _plan_delta(data, pos, 32)


def _scatter_grid(starts, takes, n_vals: int, out_len: int) -> jax.Array:
    """Per-value scatter targets for a width class with non-contiguous
    miniblock destinations, built ON DEVICE from per-miniblock starts
    and take counts (the wire carries 8 bytes per miniblock, not per
    value).  Positions past a miniblock's take count map out of bounds,
    which ``.at[].set(mode="drop")`` discards."""
    starts = jnp.asarray(starts)
    takes = jnp.asarray(takes)
    k = starts.shape[0]
    mb = n_vals // max(k, 1)
    lane = jnp.arange(mb, dtype=jnp.int32)[None, :]
    pos = starts[:, None] + lane
    pos = jnp.where(lane < takes[:, None], pos, out_len)
    return pos.reshape(-1)


def _repeat_md(md_blocks, block_size: int, n_deltas: int) -> jax.Array:
    """Per-delta min_delta lane from the per-BLOCK table (device-side
    repeat — a (n_blocks, 1) broadcast, so only 4 bytes per 128-value
    block ever cross the wire)."""
    mdb = jnp.asarray(md_blocks)
    n_blocks = mdb.shape[0]
    return jnp.repeat(
        mdb, block_size, total_repeat_length=n_blocks * block_size
    )[:n_deltas]


def expand_delta_i32(plan: DeltaPlan) -> jax.Array:
    """Device: unpack each width class, scatter into the delta stream, add
    min_delta, prefix-sum (int32 two's-complement wrap)."""
    n_deltas = max(plan.total - 1, 0)
    deltas = jnp.zeros((max(n_deltas, 1),), dtype=jnp.uint32)
    for w, words, starts, takes, n_vals, start, n_take in plan.groups:
        vals = unpack_u32(jnp.asarray(words), w, n_vals)
        if starts is None:  # contiguous destination slice
            deltas = jax.lax.dynamic_update_slice(
                deltas, vals[:n_take], (start,))
        else:
            pos = _scatter_grid(starts, takes, n_vals, deltas.shape[0])
            deltas = deltas.at[pos].set(vals[:n_vals], mode="drop")
    if plan.total == 0:
        return jnp.zeros((0,), dtype=jnp.uint32)
    first = jnp.asarray(np.uint32(plan.first & 0xFFFFFFFF))
    if n_deltas == 0:
        return first[None]
    md = _repeat_md(plan.md_lo, plan.block_size, n_deltas)
    full = deltas[:n_deltas] + md  # u32 wraparound == two's complement
    return jnp.concatenate([first[None], first + jnp.cumsum(full)])


# ----------------------------------------------------------------------
# DELTA_BINARY_PACKED (int64) — the 64-bit twin, with every 64-bit
# quantity carried as (lo, hi) u32 lanes (TPUs have no native int64;
# the reference instead duplicates its whole decoder per width,
# deltabp_decoder.go:10-12).
# ----------------------------------------------------------------------


def plan_delta_i64(data, pos: int = 0) -> DeltaPlan:
    """Parse a 64-bit DELTA_BINARY_PACKED stream (widths 0..64); same
    width-grouped miniblock layout as :func:`plan_delta_i32`."""
    return _plan_delta(data, pos, 64)


def _add64(a, b):
    """(lo, hi) u32-lane 64-bit add — associative, carried via the
    unsigned-wraparound compare."""
    lo = a[0] + b[0]
    carry = (lo < b[0]).astype(jnp.uint32)
    return lo, a[1] + b[1] + carry


@jax.jit
def _scan64_interleaved(slo, shi):
    """Inclusive 64-bit prefix sum -> flat interleaved (lo, hi) u32.
    One jit so the (n, 2) stack fuses away instead of materializing
    with a 64x-padded TPU tile layout."""
    lo, hi = jax.lax.associative_scan(_add64, (slo, shi))
    return jnp.stack([lo, hi], axis=1).reshape(-1)


def expand_delta_i64(plan: DeltaPlan) -> jax.Array:
    """Device: unpack each width class to (lo, hi) lanes, scatter into
    the delta stream, add min_delta (64-bit lane add), then an inclusive
    64-bit prefix sum via ``lax.associative_scan``.  Returns flat
    (total*2,) u32 — the interleaved (lo, hi) little-endian lane layout
    of DeviceColumn INT64."""
    from .bitunpack import unpack_u64

    if plan.total == 0:
        return jnp.zeros((0,), dtype=jnp.uint32)
    n_deltas = plan.total - 1
    first_u = plan.first & 0xFFFFFFFFFFFFFFFF
    first = jnp.asarray(
        [[np.uint32(first_u & 0xFFFFFFFF), np.uint32(first_u >> 32)]],
        dtype=jnp.uint32,
    )
    if n_deltas == 0:
        return first.reshape(-1)
    dlo = jnp.zeros((n_deltas,), dtype=jnp.uint32)
    dhi = jnp.zeros((n_deltas,), dtype=jnp.uint32)
    for w, words, starts, takes, n_vals, start, n_take in plan.groups:
        lo, hi = unpack_u64(jnp.asarray(words), w, n_vals)
        if starts is None:  # contiguous destination slice
            dlo = jax.lax.dynamic_update_slice(dlo, lo[:n_take], (start,))
            dhi = jax.lax.dynamic_update_slice(dhi, hi[:n_take], (start,))
        else:
            pos = _scatter_grid(starts, takes, n_vals, n_deltas)
            dlo = dlo.at[pos].set(lo[:n_vals], mode="drop")
            dhi = dhi.at[pos].set(hi[:n_vals], mode="drop")
    md_lo = _repeat_md(plan.md_lo, plan.block_size, n_deltas)
    md_hi = _repeat_md(plan.md_hi, plan.block_size, n_deltas)
    flo, fhi = _add64((dlo, dhi), (md_lo, md_hi))
    slo = jnp.concatenate([first[:, 0], flo])
    shi = jnp.concatenate([first[:, 1], fhi])
    return _scan64_interleaved(slo, shi)
