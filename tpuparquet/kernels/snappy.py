"""Device snappy block decompression (SURVEY.md §2.8: "Pallas Snappy
block decompressor" slot; §7 "hard parts" — byte-granular LZ copies).

Two-pass design: the host C scanner (``native/snappy.c
tpq_snappy_scan_tokens``) parses the tag stream into a token table plus
the concatenated literal bytes — O(#tokens) host work, no output
materialization — and the device resolves copies in parallel:

1. token lookup: each output byte finds its token via ``searchsorted``
   over cumulative token ends;
2. source map: literal bytes point (negatively) into the literal
   buffer, copy bytes point at a strictly-earlier output position
   (``i - offset``), so overlapping/RLE copies form chains;
3. pointer doubling: ``log2(n)`` rounds of ``m = m[m]`` shrink every
   chain to its literal root — data-independent trip count, pure
   gathers, XLA-friendly;
4. one final gather from the literal buffer.

Transfers ship only tokens + literals (<= compressed size), not the
decompressed output.  The architectural caveat: pages whose *planning*
happens on host (levels/dict-index run scans) still need host-side
bytes, so this kernel serves fully-device paths (PLAIN value segments)
and standalone device decompression; the codec registry keeps the C
host path as default.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from .decode import bucket

__all__ = ["snappy_scan_tokens", "plan_tokens", "decompress_device",
           "expand_tokens"]


def snappy_scan_tokens(block):
    """Host pass 1: (tok_out_end, tok_src, literals, out_len).

    ``block`` may be bytes / memoryview / u8 ndarray (zero-copy)."""
    from ..native import snappy_native

    nat = snappy_native()
    if nat is None:
        raise RuntimeError("native scanner unavailable (no C compiler)")
    return nat.scan_tokens(block)


def plan_tokens(block, expected_size: int | None = None):
    """Scan + pad one block's token tables for :func:`expand_tokens`.

    Returns ``(te, ts, lp, out_cap, steps, out_len)`` — int32 token
    ends/sources and u8 literals, bucket-padded (sentinels: ends=out_cap
    so padded tokens are never selected, sources=-1 resolving to literal
    0) — or None when the int32 device path would overflow.  The single
    source of the pointer-doubling preconditions, shared by
    :func:`decompress_device` and the page planner's deferred path."""
    from ..stats import current_stats

    _st = current_stats()
    _t0 = time.perf_counter() if _st is not None else 0.0
    tok_end, tok_src, lits, out_len = snappy_scan_tokens(block)
    if _st is not None:
        # the token scan is a third of the plan wall (see the lazy-scan
        # comment in kernels/device.py) — its distribution says whether
        # the laziness is still paying
        _st.hist("snappy_scan_us").record(
            (time.perf_counter() - _t0) * 1e6)
        _st.hist("snappy_tokens_per_page").record(len(tok_end))
    if expected_size is not None and out_len != expected_size:
        raise ValueError(
            f"snappy: header size {out_len} != expected {expected_size}"
        )
    out_cap = bucket(out_len)
    if out_cap >= 1 << 31:  # int32 token table would wrap
        return None
    T = bucket(len(tok_end))
    te = np.full(T, out_cap, dtype=np.int32)
    te[: len(tok_end)] = tok_end
    ts = np.full(T, -1, dtype=np.int32)
    ts[: len(tok_src)] = tok_src
    lp = np.zeros(bucket(max(len(lits), 1)), dtype=np.uint8)
    lp[: len(lits)] = lits
    # chains shorten by >= 1 output position per unresolved hop, and
    # every hop at least doubles resolved coverage: ceil(log2(n)) rounds
    steps = max(int(np.ceil(np.log2(max(out_len, 2)))), 1)
    return te, ts, lp, out_cap, steps, out_len


@functools.partial(jax.jit, static_argnames=("out_cap", "steps"))
def expand_tokens(tok_end, tok_src, lits, out_cap: int, steps: int):
    """Device pass 2: resolve the copy graph; returns (out_cap,) u8
    (caller slices to the real length).  int32 throughout — parquet
    pages are far below 2 GiB."""
    i = jnp.arange(out_cap, dtype=jnp.int32)
    t = jnp.searchsorted(tok_end, i, side="right")
    t = jnp.minimum(t, tok_end.shape[0] - 1)
    start = jnp.where(t > 0, tok_end[t - 1], 0)
    within = i - start
    src = tok_src[t]
    # m[i]: immediate source — negative = -(literal index)-1 (resolved),
    # >= 0 = earlier output position (unresolved copy link)
    m = jnp.where(src < 0, src - within, src + within)

    def round_(_, mm):
        nxt = mm[jnp.clip(mm, 0, out_cap - 1)]
        return jnp.where(mm >= 0, nxt, mm)

    m = jax.lax.fori_loop(0, steps, round_, m)
    lit_idx = jnp.clip(-(m + 1), 0, lits.shape[0] - 1)
    return lits[lit_idx]


def decompress_device(block: bytes, expected_size: int | None = None):
    """Decompress one snappy block to a device-resident u8 array."""
    plan = plan_tokens(block, expected_size)
    if plan is None:
        raise ValueError("device snappy: block too large for int32 path")
    te, ts, lp, out_cap, steps, out_len = plan
    if out_len == 0:
        return jnp.zeros((0,), dtype=jnp.uint8)
    staged = jax.device_put((te, ts, lp))
    out = expand_tokens(*staged, out_cap, steps)
    return out[:out_len]
