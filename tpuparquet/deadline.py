"""Deadline-aware execution: watchdog timeouts and hedged reads.

Rounds 7–8 made scans robust to corrupt *bytes*; this module covers
the *time* domain.  A production input pipeline on preemptible TPU VMs
sees reads that never return (hung NFS mounts, stalled object-store
connections) and device dispatches that wedge — and a single hung
operation must become a bounded, classified failure that flows into
the established retry → CPU-fallback → quarantine ladder instead of
stalling the fleet forever.

Three moving parts:

* **Watchdog** — one daemon thread (:func:`watchdog`, lazily started)
  scans a registry of in-flight watched operations and flips any that
  run past their budget to "expired", waking the waiter.  The waiter
  raises the deadline error on ITS OWN thread (counters are
  thread-local; only the waiter knows its collector).  A hung read
  becomes :class:`~tpuparquet.errors.DeadlineExceededError` (a
  ``TransientIOError`` — retried/hedged); a hung dispatch becomes
  :class:`~tpuparquet.errors.DispatchDeadlineError` (a
  ``DeviceDispatchError`` — dispatch-retried, then degraded to the
  bit-exact CPU decode).
* **call_with_deadline** — run a callable bounded by a budget: the
  work runs on a disposable worker thread registered with the
  watchdog; on expiry the worker is *abandoned* (daemon — Python
  cannot interrupt a blocked C-level read) and the deadline error is
  raised with ``elapsed``/``budget``/coordinates.  The abandoned
  worker's eventual result and stats are discarded whole (a merged
  half-count would be worse than none).
* **hedged_call** — "The Tail at Scale" (Dean & Barroso, CACM 2013)
  hedged requests: run the primary; if it hasn't completed after a
  hedge delay, duplicate the work against the next replica; first
  SUCCESS wins, losers are abandoned.  The default delay is the
  rolling p95 of observed read latency (:class:`LatencyTracker` /
  :data:`read_latency`), which caps the added replica load at ~5%.
  Bit-exactness across replicas is enforced by the page CRC path —
  a diverging mirror fails CRC exactly like corruption.

Env knobs: ``TPQ_UNIT_DEADLINE_S`` (per-unit scan budget),
``TPQ_SCAN_DEADLINE_S`` (whole-scan budget), ``TPQ_READ_DEADLINE_S``
(per chunk-read budget), ``TPQ_DISPATCH_DEADLINE_S`` (per device
dispatch attempt), ``TPQ_HEDGE_DELAY_S`` (fixed hedge delay; unset =
adaptive p95).  All default off/adaptive — the fast path with no
budgets configured is the exact pre-round behavior (no threads, no
watchdog).

Counters (``DecodeStats``): ``deadline_exceeded``, ``hedges_issued``,
``hedges_won`` — merged exactly across threads and hosts like the
round-7 set.  Every expiry/hedge also lands a fault record on the
event log (kinds ``deadline_exceeded`` / ``hedge_issued`` /
``hedge_won``) carrying the site and coordinates.
"""

from __future__ import annotations

import atexit
import os
import queue
import threading
import time
import weakref

from .errors import DeadlineExceededError

__all__ = [
    "Watchdog",
    "watchdog",
    "call_with_deadline",
    "hedged_call",
    "record_expiry",
    "LatencyTracker",
    "read_latency",
    "unit_deadline_default",
    "scan_deadline_default",
    "read_deadline_default",
    "dispatch_deadline_default",
    "hedge_delay_default",
]

_COORD_KEYS = ("file", "row_group", "column", "page")


def _env_budget(name: str) -> float | None:
    """A seconds budget from the environment; unset/invalid/<=0 = off."""
    try:
        v = float(os.environ.get(name, ""))
    except ValueError:
        return None
    return v if v > 0 else None


def unit_deadline_default() -> float | None:
    """Per-scan-unit budget (``TPQ_UNIT_DEADLINE_S``); None = off."""
    return _env_budget("TPQ_UNIT_DEADLINE_S")


def scan_deadline_default() -> float | None:
    """Whole-scan budget (``TPQ_SCAN_DEADLINE_S``); None = off."""
    return _env_budget("TPQ_SCAN_DEADLINE_S")


def read_deadline_default() -> float | None:
    """Per chunk-read budget (``TPQ_READ_DEADLINE_S``); None = off."""
    return _env_budget("TPQ_READ_DEADLINE_S")


def dispatch_deadline_default() -> float | None:
    """Per device-dispatch-attempt budget
    (``TPQ_DISPATCH_DEADLINE_S``); None = off."""
    return _env_budget("TPQ_DISPATCH_DEADLINE_S")


def hedge_delay_default() -> float | None:
    """Fixed hedge delay (``TPQ_HEDGE_DELAY_S``); None = adaptive
    (rolling p95 of observed read latency)."""
    return _env_budget("TPQ_HEDGE_DELAY_S")


# ----------------------------------------------------------------------
# Watchdog
# ----------------------------------------------------------------------

class _Op:
    """One in-flight watched operation."""

    __slots__ = ("site", "budget", "deadline", "coords", "event",
                 "state")

    def __init__(self, site: str, budget: float, coords: dict):
        self.site = site
        self.budget = budget
        self.deadline = 0.0      # monotonic expiry, set at register time
        self.coords = coords
        self.event = threading.Event()
        self.state = "pending"   # -> "done" | "expired" (watchdog lock)


class Watchdog(threading.Thread):
    """Daemon thread that expires in-flight ops past their budget.

    State transitions (``pending -> done`` by the worker, ``pending ->
    expired`` by the watchdog) are serialized under one condition
    variable, so a result racing an expiry resolves to exactly one
    winner.  With no registered ops the thread sleeps until the next
    :meth:`register` — an idle process pays nothing."""

    def __init__(self):
        super().__init__(name="tpq-watchdog", daemon=True)
        self._cv = threading.Condition()
        self._ops: set[_Op] = set()

    def register(self, op: _Op) -> None:
        op.deadline = time.monotonic() + op.budget
        with self._cv:
            self._ops.add(op)
            self._cv.notify()

    def finish(self, op: _Op) -> bool:
        """Worker completed: True if the op was still pending (its
        result counts); False if already expired (abandoned)."""
        with self._cv:
            self._ops.discard(op)
            if op.state == "pending":
                op.state = "done"
                op.event.set()
                return True
            return False

    def expire(self, op: _Op) -> bool:
        """Force-expire (the waiter's dead-watchdog fallback)."""
        with self._cv:
            self._ops.discard(op)
            if op.state == "pending":
                op.state = "expired"
                op.event.set()
                return True
            return False

    def run(self):
        while True:
            with self._cv:
                now = time.monotonic()
                nxt = None
                for op in list(self._ops):
                    if now >= op.deadline:
                        self._ops.discard(op)
                        op.state = "expired"
                        op.event.set()
                    elif nxt is None or op.deadline < nxt:
                        nxt = op.deadline
                self._cv.wait(
                    None if nxt is None
                    else max(nxt - time.monotonic(), 0.001))


_watchdog: Watchdog | None = None
_watchdog_lock = threading.Lock()


def watchdog() -> Watchdog:
    """The process singleton, started lazily (and restarted after a
    fork killed it — threads do not survive fork)."""
    global _watchdog
    w = _watchdog
    if w is not None and w.is_alive():
        return w
    with _watchdog_lock:
        w = _watchdog
        if w is None or not w.is_alive():
            w = Watchdog()
            w.start()
            _watchdog = w
    return w


# ----------------------------------------------------------------------
# Worker threads (deadline + hedge branches)
# ----------------------------------------------------------------------

#: Live worker threads this module spawned.  Abandoned workers are
#: daemons (Python cannot interrupt a blocked C-level read), and a
#: daemon killed mid-XLA-call at interpreter shutdown aborts the
#: process ("terminate called without an active exception") — so exit
#: drains them with a bounded grace first.  A worker hung past the
#: grace falls back to the daemon kill; the grace covers the common
#: case where the slow operation completed shortly after being
#: abandoned.
_workers: "weakref.WeakSet[threading.Thread]" = weakref.WeakSet()
# WeakSet is pure Python and not thread-safe; hedge/deadline
# coordinators on different threads spawn workers concurrently, and
# an unguarded add can race the GC-driven discard of a dead worker's
# weakref (and the exit drain's iteration) inside the set's own
# bookkeeping
_workers_lock = threading.Lock()
_EXIT_GRACE_S = 5.0


def _spawn_worker(target, name: str) -> threading.Thread:
    t = threading.Thread(target=target, daemon=True, name=name)
    with _workers_lock:
        _workers.add(t)
    t.start()
    return t


@atexit.register
def _drain_workers_at_exit() -> None:
    stop_at = time.monotonic() + _EXIT_GRACE_S
    with _workers_lock:
        pending = list(_workers)
    for t in pending:
        t.join(max(stop_at - time.monotonic(), 0.0))


# ----------------------------------------------------------------------
# Deadline-bounded call
# ----------------------------------------------------------------------

def _merge_worker(st, ws, failed: bool) -> None:
    from .stats import merge_worker_stats

    merge_worker_stats(st, ws, failed=failed)


def record_expiry(st, site: str, elapsed: float, budget: float,
                  coords: dict) -> None:
    """Record one deadline expiry on a collector: the
    ``deadline_exceeded`` counter plus the matching fault event —
    the single owner of the expiry-recording contract (used by the
    watchdog paths here and the scan-level budget in
    ``shard.scan.DurableScanMixin``)."""
    from .obs import digest as _digest
    from .obs.recorder import flight
    from .obs.trace import emit_span

    # the flight recorder sees every expiry, collector or not — this
    # is exactly the record a post-mortem wants on its timeline
    flight("deadline_exceeded", site=site,
           elapsed_s=round(elapsed, 3), budget_s=budget, **coords)
    # the causal trace sees it too: a zero-duration error span at the
    # expiry instant, parented under whatever stage was waiting
    emit_span("deadline_exceeded", time.perf_counter(), 0.0,
              status="error", site=site, elapsed_s=round(elapsed, 3),
              budget_s=budget, **coords)
    # and the latency digest: the expired wall lands in the site's
    # distribution (it IS the tail the SLO is about), keyed under the
    # deadline stage so it never pollutes the unit/scan series
    if _digest._active is not None:
        _digest.observe("deadline", site, int(elapsed * 1e6),
                        budget_s=budget, **_scan_coords(coords))
    if st is None:
        return
    st.deadline_exceeded += 1
    if st.events is not None:
        st.events.fault(site=site, kind="deadline_exceeded",
                        elapsed_s=round(elapsed, 3), budget_s=budget,
                        **coords)


def _scan_coords(coords: dict) -> dict:
    return {k: coords[k] for k in _COORD_KEYS if k in coords}


def call_with_deadline(fn, budget: float | None, *, site: str,
                       error=DeadlineExceededError, **coords):
    """Run ``fn()`` bounded by ``budget`` seconds.

    ``budget`` None/<=0 is a plain call — zero overhead, no threads.
    Otherwise ``fn`` runs on a disposable daemon worker registered
    with the :func:`watchdog`; if it completes in time its result (or
    exception) propagates and its thread-local stats merge into the
    caller's collector.  On expiry the worker is abandoned and
    ``error`` is raised carrying ``elapsed``/``budget``/``site`` and
    the scan ``coords``; the caller's ``deadline_exceeded`` counter
    increments and a fault event is recorded."""
    if budget is None or budget <= 0:
        return fn()
    from .obs import trace as _trace
    from .serve import arbiter as _arbiter
    from .stats import current_stats

    st = current_stats()
    op = _Op(site, budget, coords)
    box: dict = {}
    wd = watchdog()
    # the disposable worker re-enters the caller's trace context so
    # spans emitted by the bounded work parent causally under the
    # caller's open span (unit, plan, ...) despite the thread hop —
    # and the caller's serve-tenant binding, so the bounded work's
    # planner pool sizes from the tenant's arbiter share
    tctx = _trace.current_ctx()
    tenant = _arbiter.current_binding()

    def run():
        from .stats import worker_stats

        try:
            with _trace.adopt(tctx), _arbiter.tenant_scope(tenant), \
                    worker_stats(like=st) as ws:
                try:
                    box["result"] = fn()
                except BaseException as e:  # noqa: BLE001 — repropagated
                    box["error"] = e
            box["stats"] = ws
        finally:
            wd.finish(op)

    start = time.monotonic()
    wd.register(op)
    _spawn_worker(run, f"tpq-deadline:{site}")
    # the watchdog (or the worker) sets the event; the slack covers a
    # wedged watchdog — the waiter itself never blocks forever
    if not op.event.wait(budget + 1.0):
        wd.expire(op)
        op.event.wait(0.1)
    if op.state == "done":
        err = box.get("error")
        _merge_worker(st, box.get("stats"), failed=err is not None)
        if err is not None:
            raise err
        return box["result"]
    elapsed = time.monotonic() - start
    record_expiry(st, site, elapsed, budget, coords)
    raise error(
        f"{site} exceeded its {budget:g}s deadline "
        f"(hung for {elapsed:.3f}s)",
        elapsed=elapsed, budget=budget, site=site,
        **_scan_coords(coords))


# ----------------------------------------------------------------------
# Hedged calls
# ----------------------------------------------------------------------

def hedged_call(fns, *, delay: float, site: str,
                budget: float | None = None, tracker=None,
                on_win=None, **coords):
    """Tail-at-scale hedging over replica callables.

    ``fns[0]`` (the primary) starts immediately; every time ``delay``
    seconds pass with no completed branch — or a branch *fails* — the
    next replica launches.  The first branch to SUCCEED wins: its
    result returns, its stats merge, its latency is recorded into
    ``tracker``, and slower branches are abandoned (replica reads are
    byte-identical by contract; the page CRC path catches a mirror
    that diverges).  If every launched branch fails, the primary
    branch's error (or the first seen) re-raises.  ``budget``
    optionally bounds the TOTAL wall — expiry raises
    :class:`~tpuparquet.errors.DeadlineExceededError` exactly like
    :func:`call_with_deadline`.

    Counters: ``hedges_issued`` per extra branch launched,
    ``hedges_won`` when a non-primary branch's result is used, with
    matching ``hedge_issued``/``hedge_won`` fault events.  ``on_win``
    (optional) is called with the winning branch index before
    returning — callers use it to track which replica is actually
    serving (e.g. the reader's wedged-primary detection)."""
    fns = list(fns)
    if len(fns) == 1 and (budget is None or budget <= 0):
        return fns[0]()
    from .obs import trace as _trace
    from .serve import arbiter as _arbiter
    from .stats import current_stats, worker_stats

    st = current_stats()
    tenant = _arbiter.current_binding()
    q: queue.SimpleQueue = queue.SimpleQueue()
    starts: dict[int, float] = {}
    # per-branch trace spans: each launched replica gets an open span
    # under the caller's context; the branch worker adopts ITS span's
    # context, so the branch's own reads nest under it.  Resolution
    # closes the winner "ok" and every abandoned sibling "cancelled" —
    # hedge losers are visible, attributable child spans, not ghosts.
    branch_spans: dict[int, object] = {}

    def launch(i: int) -> None:
        starts[i] = time.monotonic()
        bsp = None
        if _trace._active is not None:
            bsp = _trace.open_span("read_replica", push=False,
                                   replica=i, site=site, **coords)
        branch_spans[i] = bsp
        bctx = _trace.ctx_of(bsp)

        def run():
            try:
                with _trace.adopt(bctx), _arbiter.tenant_scope(tenant), \
                        worker_stats(like=st) as ws:
                    try:
                        out = (True, fns[i]())
                    except BaseException as e:  # noqa: BLE001
                        out = (False, e)
                q.put((i, out[0], out[1], ws))
            except BaseException:  # interpreter teardown; drop
                pass

        _spawn_worker(run, f"tpq-hedge:{site}:{i}")

    def _close_branch(i: int, status: str) -> None:
        bsp = branch_spans.pop(i, None)
        if bsp is not None:
            _trace.close_span(bsp, status=status)

    def hedge_next() -> None:
        from .obs.recorder import flight

        i = len(starts)
        flight("hedge_issued", site=site, replica=i, **coords)
        if st is not None:
            st.hedges_issued += 1
            if st.events is not None:
                st.events.fault(site=site, kind="hedge_issued",
                                replica=i, **coords)
        launch(i)

    t0 = time.monotonic()
    launch(0)
    errors: dict[int, BaseException] = {}
    done = 0
    while True:
        now = time.monotonic()
        if budget is not None and budget > 0 and now - t0 >= budget:
            elapsed = now - t0
            for i in list(branch_spans):
                _close_branch(i, "cancelled")
            record_expiry(st, site, elapsed, budget, coords)
            raise DeadlineExceededError(
                f"{site} exceeded its {budget:g}s deadline with "
                f"{len(starts) - done} hedged read(s) still hung",
                elapsed=elapsed, budget=budget, site=site,
                **_scan_coords(coords))
        wait = None
        if len(starts) < len(fns):
            wait = max(t0 + len(starts) * delay - now, 0.0)
        if budget is not None and budget > 0:
            remaining = max(t0 + budget - now, 0.001)
            wait = remaining if wait is None else min(wait, remaining)
        try:
            i, ok, val, ws = q.get(timeout=wait)
        except queue.Empty:
            # only hedge when the hedge delay has actually elapsed — a
            # wait clipped by the BUDGET must not issue a spurious
            # replica read right before the deadline raise
            if len(starts) < len(fns) and \
                    time.monotonic() >= t0 + len(starts) * delay:
                hedge_next()
            continue
        if ok:
            _merge_worker(st, ws, failed=False)
            if tracker is not None:
                tracker.record(time.monotonic() - starts[i])
            _close_branch(i, "ok")
            for j in list(branch_spans):
                _close_branch(j, "cancelled")  # abandoned losers
            if i > 0:
                from .obs import recorder as _flightrec

                if _flightrec._active is not None:
                    _flightrec.flight("hedge_won", site=site,
                                      replica=i, **coords)
                if st is not None:
                    st.hedges_won += 1
                    if st.events is not None:
                        st.events.fault(site=site, kind="hedge_won",
                                        replica=i, **coords)
            if on_win is not None:
                on_win(i)
            return val
        _merge_worker(st, ws, failed=True)
        _close_branch(i, "error")
        errors[i] = val
        done += 1
        if done == len(starts):
            if len(starts) < len(fns):
                hedge_next()     # every launched branch failed: escalate
                continue
            raise errors.get(0, next(iter(errors.values())))


# ----------------------------------------------------------------------
# Rolling read-latency tracker (adaptive hedge delay)
# ----------------------------------------------------------------------

class LatencyTracker:
    """Rolling window of observed operation latencies.

    ``hedge_delay()`` returns the window p95 (floored) once enough
    samples exist — hedging at ~p95 bounds extra replica load at ~5%
    (The Tail at Scale) — and a conservative fixed default before
    that.  Thread-safe; recording is O(1), the quantile sorts the
    (small, bounded) window on demand."""

    def __init__(self, window: int = 256, floor: float = 0.002,
                 default: float = 0.05, min_samples: int = 8):
        self._window = window
        self._floor = floor
        self._default = default
        self._min_samples = min_samples
        self._buf: list[float] = []
        self._pos = 0
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        with self._lock:
            if len(self._buf) < self._window:
                self._buf.append(seconds)
            else:
                self._buf[self._pos] = seconds
                self._pos = (self._pos + 1) % self._window


    def __len__(self) -> int:
        return len(self._buf)

    def quantile(self, q: float) -> float | None:
        with self._lock:
            if not self._buf:
                return None
            s = sorted(self._buf)
        i = min(int(q * len(s)), len(s) - 1)
        return s[i]

    def hedge_delay(self) -> float:
        if len(self._buf) < self._min_samples:
            return self._default
        return max(self.quantile(0.95), self._floor)

    def reset(self) -> None:
        with self._lock:
            self._buf = []
            self._pos = 0


#: Process-global rolling window of chunk-read latencies: every
#: FileReader records into it, so the adaptive hedge delay reflects
#: the store's CURRENT tail, not one file's history.
read_latency = LatencyTracker()
